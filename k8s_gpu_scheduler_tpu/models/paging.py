"""Host-side KV page allocator for the paged serving cache.

The paged ContinuousBatcher (models/serving.py) replaces the shared
scalar cursor with a pool of fixed-size KV pages and a per-slot block
table: admission needs FREE PAGES, not a contiguous window, so a prompt
admits the moment enough requests have finished — no backward-write
trick, no epoch roll, no all-slots-drained idle boundary. This module is
the allocator half of that design: a plain LIFO free list (recently
freed pages are re-written soonest — friendliest to whatever HBM pages
are still warm) with watermark/churn metrics the bench and the serving
entrypoint publish.

Page 0 is RESERVED as the null/scratch page: device-side writes for
inactive slots and the over-provisioned tail of a padded prefill scatter
are redirected there (a fixed, never-handed-out target keeps those
writes branch-free on device), and zeroed block-table rows point at it.
Its contents are garbage by design and never attended — every read of it
is masked by the length bound.

Allocation is all-or-nothing and WORST-CASE at admission: the batcher
reserves ceil((prompt + decode rows)/page_size) pages up front, so a
request in flight can never stall mid-decode waiting for a page another
stuck request holds (no allocation deadlock), at the cost of eos
early-stop releasing its unused tail only at finish. Free is immediate
and exact — the fragmentation the contiguous cursor design pays (stale
epochs, bucket-ladder re-dispatch, roll stalls) simply has no analog
here.
"""
from __future__ import annotations

from typing import Dict, List, Optional

NULL_PAGE = 0


class PageAllocator:
    """Fixed-size page pool bookkeeping. ``n_pages`` counts the WHOLE pool
    including the reserved null page, so a pool of n_pages has
    ``n_pages - 1`` usable pages."""

    def __init__(self, n_pages: int) -> None:
        if n_pages < 2:
            raise ValueError(
                f"need >= 2 pages (one is the reserved null page), got "
                f"{n_pages}")
        self.n_pages = n_pages
        # LIFO: freed pages are reused first.
        self._free: List[int] = list(range(n_pages - 1, NULL_PAGE, -1))
        self._held: set = set()              # pages currently allocated
        self._watermark = 0
        self._allocs = 0
        self._frees = 0
        self._denied = 0

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return len(self._held)

    def alloc(self, n: int,
              count_denied: bool = True) -> Optional[List[int]]:
        """n pages, or None when fewer than n are free (all-or-nothing —
        a partial grant could deadlock two admissions against each
        other). ``count_denied=False`` suppresses the denial counter for
        RETRIES of an already-counted request — the batcher re-attempts
        its blocked queue head every decode step, and counting each
        retry would report a thousand denials for one waiting request."""
        if n < 0:
            raise ValueError(f"negative page count {n}")
        if n > len(self._free):
            if count_denied:
                self._denied += 1
            return None
        pages, self._free = self._free[len(self._free) - n:], \
            self._free[:len(self._free) - n]
        pages.reverse()                      # LIFO pop order, stable ids
        self._held.update(pages)
        self._watermark = max(self._watermark, len(self._held))
        self._allocs += n
        return pages

    def free(self, pages: List[int]) -> None:
        """Return pages to the pool. Per-page validated BEFORE any state
        mutates: a double free (or freeing a page this allocator never
        handed out) would put the same id on the free list twice, handing
        one physical page to two future requests — silent KV
        cross-contamination, the worst possible failure mode."""
        for p in pages:
            if p == NULL_PAGE:
                raise ValueError("cannot free the reserved null page")
            if p not in self._held:
                raise RuntimeError(
                    f"double free (or foreign page): page {p} is not "
                    f"currently allocated")
        for p in pages:
            self._held.discard(p)
            self._free.append(p)
        self._frees += len(pages)

    def metrics(self) -> Dict[str, float]:
        """Allocator state for the bench/Observation publishers. The
        utilization is instantaneous (pages now held / usable pool);
        the watermark is the high-water mark since construction."""
        usable = self.n_pages - 1
        return {
            "pages_total": float(usable),
            "pages_free": float(len(self._free)),
            "pages_in_use": float(len(self._held)),
            "pages_watermark": float(self._watermark),
            "page_allocs": float(self._allocs),
            "page_frees": float(self._frees),
            "page_denied": float(self._denied),
            "page_utilization": (len(self._held) / usable) if usable else 0.0,
        }
