"""Host-side KV page allocator for the paged serving cache.

The paged ContinuousBatcher (models/serving.py) replaces the shared
scalar cursor with a pool of fixed-size KV pages and a per-slot block
table: admission needs FREE PAGES, not a contiguous window, so a prompt
admits the moment enough requests have finished — no backward-write
trick, no epoch roll, no all-slots-drained idle boundary. This module is
the allocator half of that design: a LIFO free list (recently freed
pages are re-written soonest — friendliest to whatever HBM pages are
still warm) with watermark/churn metrics the bench and the serving
entrypoint publish.

Since the prefix cache landed (models/prefix_cache.py) pages are
REF-COUNTED: one physical page can back the block tables of many slots
at once (a shared system-prompt prefix — or, since the decoded-suffix
donation, a whole previous conversation turn: reaped requests donate
their prompt AND resident decoded pages, so multi-turn follow-ups mount
the entire transcript) plus a reference held by the radix tree itself. ``alloc`` hands out pages at refcount 1, ``retain``
adds a holder, ``free`` drops one — a page returns to the free list only
when its LAST reference drops. The tree's reference is labeled via
``adopt``/``drop_cached`` so the pool partitions cleanly into
free / held / cached for the ``assert_consistent`` invariant check.

Page 0 is RESERVED as the null/scratch page: device-side writes for
inactive slots and the over-provisioned tail of a padded prefill scatter
are redirected there (a fixed, never-handed-out target keeps those
writes branch-free on device), and zeroed block-table rows point at it.
Its contents are garbage by design and never attended — every read of it
is masked by the length bound.

Allocation is all-or-nothing and WORST-CASE at admission: the batcher
reserves ceil((prompt + decode rows)/page_size) pages up front, so a
request in flight can never stall mid-decode waiting for a page another
stuck request holds (no allocation deadlock), at the cost of eos
early-stop releasing its unused tail only at finish. In SPECULATIVE mode
the decode-row term grows by the verify-window overshoot
(serving.ContinuousBatcher ._rows_needed/_spec_overshoot): every verify
dispatch writes up to its effective window past the committed ``lens``
but commits only the accepted prefix, so the rejected rows overshoot it
— the reservation guarantees ACCEPTED rows land in pages THIS slot
already owns, which is why rewind is a pure lens clamp: no page changes
hands, no shared (prefix-cache) page is ever written, and the overshoot
pages return through the ordinary ``free`` at finish like any
reservation slack. Under ADAPTIVE gamma the overshoot term is sized per
request from the fleet accept-rate EMA and PINNED at submit
(``_spec_reserve`` — it rides the snapshot), and the per-dispatch
effective window is capped at that pin: low-accept traffic stops
hoarding overshoot pages it never lands, without the reservation
invariant ever weakening. Free is immediate and exact — the
fragmentation the contiguous cursor design pays (stale epochs,
bucket-ladder re-dispatch, roll stalls) simply has no analog here.
"""
from __future__ import annotations

import os
from collections import OrderedDict
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

NULL_PAGE = 0


class HostTierStore:
    """Host-DRAM second tier (plus an optional disk third tier) for
    DEMOTED KV pages — the off-pool half of the tiered cache
    (vLLM-CPU-offload / LMCache-style hierarchy) behind the radix tree.

    Entries are keyed by monotonic ints (a separate namespace from pool
    page ids — a tier key is never a valid block-table entry) and hold a
    page's host-numpy payload ``(k, v, k_scale, v_scale)`` with the
    scale planes ``None`` for non-int8 engines. Lifecycle:

    - ``reserve(page)`` registers a PENDING demotion: the tree node is
      already tier-flagged but the bytes still live in the pool page,
      which stays allocated+cached until the engine's device→host
      readback queue drains at a step boundary (the pool is donated
      every dispatch, so the copy can never run inside one). A pending
      entry can be ``cancel``-ed — the mid-match race where a new
      request lands on the path before the drain: the retain pin wins
      and the demotion is undone for free.
    - ``commit(key, payload)`` lands the gathered bytes in DRAM. Over
      ``dram_pages`` capacity the coldest COMMITTED entry that
      ``can_evict`` approves is shed first — spilled to the disk tier
      when ``disk_dir`` is set (demote-before-forget, disk only when
      DRAM is full), forgotten otherwise (``on_drop`` lets the tree
      prune the node). If nothing is evictable the INCOMING entry is
      refused (returns False) and the caller forgets it instead.
    - ``pop(key)`` removes and returns the payload for promotion back
      into freshly-reserved pool pages, transparently loading a
      disk-spilled entry.

    ``can_evict`` exists because only CHILDLESS demoted leaves may
    leave the tier: dropping a mid-path node would strand descendants
    whose match walk can no longer reach them.
    """

    def __init__(self, dram_pages: int,
                 disk_dir: Optional[str] = None) -> None:
        if dram_pages < 1:
            raise ValueError(f"dram_pages must be >= 1, got {dram_pages}")
        self.dram_pages = int(dram_pages)
        self.disk_dir = disk_dir
        self._next_key = 0
        self._pending: "OrderedDict[int, int]" = OrderedDict()  # key -> page
        self._dram: "OrderedDict[int, tuple]" = OrderedDict()   # key -> payload
        self._disk: Set[int] = set()
        # Tier-policy callbacks the radix tree installs (see class doc).
        self.can_evict: Callable[[int], bool] = lambda key: True
        self.on_drop: Callable[[int], None] = lambda key: None
        self._demotions = 0                  # commits (pages landed in DRAM)
        self._spills = 0                     # DRAM -> disk
        self._forgotten = 0                  # shed with nowhere to go
        self._cancelled = 0                  # pending demotions undone

    def __len__(self) -> int:
        """Committed pages in the tier (DRAM + disk)."""
        return len(self._dram) + len(self._disk)

    @property
    def dram_count(self) -> int:
        return len(self._dram)

    @property
    def disk_count(self) -> int:
        return len(self._disk)

    def has(self, key: int) -> bool:
        return key in self._dram or key in self._disk

    def is_pending(self, key: int) -> bool:
        return key in self._pending

    def reserve(self, page: int) -> int:
        """Register a pending demotion of pool ``page``; returns the new
        tier key. The page's bytes are copied later (``commit``) by the
        step-boundary readback drain."""
        key = self._next_key
        self._next_key += 1
        self._pending[key] = int(page)
        return key

    def cancel(self, key: int) -> int:
        """Undo a pending demotion (the page was re-matched before the
        drain — retain pins win); returns the pool page to restore."""
        page = self._pending.pop(key)
        self._cancelled += 1
        return page

    def take_pending(self) -> List[Tuple[int, int]]:
        """Drain the pending queue: ``(key, page)`` pairs in demotion
        order. Called by the engine at a step boundary with the gathered
        bytes committed per pair."""
        out = list(self._pending.items())
        self._pending.clear()
        return out

    def _disk_path(self, key: int) -> str:
        return os.path.join(self.disk_dir, f"kvpage_{key}.npz")

    def _shed_coldest(self) -> bool:
        """Make room for one entry: spill (or forget) the coldest
        evictable committed entry. False when nothing is evictable."""
        import numpy as np

        for key in self._dram:               # insertion order == coldest first
            if not self.can_evict(key):
                continue
            payload = self._dram.pop(key)
            if self.disk_dir is not None:
                k, v, ks, vs = payload
                os.makedirs(self.disk_dir, exist_ok=True)
                arrs = {"k": k, "v": v}
                if ks is not None:
                    arrs.update(ks=ks, vs=vs)
                np.savez(self._disk_path(key), **arrs)
                self._disk.add(key)
                self._spills += 1
            else:
                self._forgotten += 1
                self.on_drop(key)
            return True
        return False

    def commit(self, key: int, payload: tuple) -> bool:
        """Land gathered page bytes for a pending ``key`` in DRAM,
        shedding the coldest evictable entry first when at capacity.
        Returns False (entry refused, caller forgets the node) when the
        tier is full and nothing can be shed."""
        self._pending.pop(key, None)
        while len(self._dram) >= self.dram_pages:
            if not self._shed_coldest():
                self._forgotten += 1
                return False
        self._dram[key] = payload
        self._demotions += 1
        return True

    def restore_entry(self, payload: tuple) -> Optional[int]:
        """Snapshot-restore path: admit an already-gathered payload under
        a fresh key (counts as a demotion landing). None when refused."""
        key = self._next_key
        self._next_key += 1
        return key if self.commit(key, payload) else None

    def touch(self, key: int) -> None:
        """LRU bump on a match walk through the demoted node."""
        if key in self._dram:
            self._dram.move_to_end(key)

    def pop(self, key: int) -> tuple:
        """Remove and return ``key``'s payload for promotion (loads a
        disk-spilled entry back through DRAM transparently)."""
        import numpy as np

        if key in self._dram:
            return self._dram.pop(key)
        if key in self._disk:
            self._disk.discard(key)
            path = self._disk_path(key)
            with np.load(path) as z:
                payload = (z["k"], z["v"],
                           z["ks"] if "ks" in z else None,
                           z["vs"] if "vs" in z else None)
            os.remove(path)
            return payload
        raise KeyError(f"tier key {key} is not committed")

    def discard(self, key: int) -> None:
        """Drop an entry without reading it (the chunk became resident
        again via a donated page carrying the same bytes)."""
        if key in self._dram:
            del self._dram[key]
        elif key in self._disk:
            self._disk.discard(key)
            try:
                os.remove(self._disk_path(key))
            except OSError:
                pass

    def items_coldest_first(self) -> List[Tuple[int, tuple]]:
        """Committed DRAM entries, coldest first — the serializable
        order a drain snapshot carries (disk-spilled entries are loaded
        too, coldest of all: they were shed before everything in DRAM)."""
        import numpy as np

        out = []
        for key in sorted(self._disk):       # read-only: tier unchanged
            with np.load(self._disk_path(key)) as z:
                out.append((key, (z["k"], z["v"],
                                  z["ks"] if "ks" in z else None,
                                  z["vs"] if "vs" in z else None)))
        out.extend(self._dram.items())
        return out

    def assert_consistent(self) -> None:
        """Tier invariants: pending/DRAM/disk key sets are disjoint,
        DRAM within capacity, keys below the monotonic cursor."""
        dram, disk, pend = set(self._dram), self._disk, set(self._pending)
        for a, b, what in ((dram, disk, "DRAM∩disk"),
                           (dram, pend, "DRAM∩pending"),
                           (disk, pend, "disk∩pending")):
            if a & b:
                raise RuntimeError(f"tier key in two states ({what}): "
                                   f"{sorted(a & b)}")
        if len(self._dram) > self.dram_pages:
            raise RuntimeError(
                f"DRAM tier over capacity: {len(self._dram)} > "
                f"{self.dram_pages}")
        over = [k for k in dram | disk | pend if k >= self._next_key]
        if over:
            raise RuntimeError(f"tier keys beyond cursor: {sorted(over)}")

    def metrics(self) -> Dict[str, float]:
        return {
            "tier_dram_pages": float(len(self._dram)),
            "tier_dram_capacity": float(self.dram_pages),
            "tier_disk_pages": float(len(self._disk)),
            "tier_pending_demotions": float(len(self._pending)),
            "page_demotions_total": float(self._demotions),
            "tier_spills_total": float(self._spills),
            "tier_forgotten_total": float(self._forgotten),
            "tier_cancelled_demotions": float(self._cancelled),
        }


class PageAllocator:
    """Fixed-size page pool bookkeeping. ``n_pages`` counts the WHOLE pool
    including the reserved null page, so a pool of n_pages has
    ``n_pages - 1`` usable pages."""

    def __init__(self, n_pages: int) -> None:
        if n_pages < 2:
            raise ValueError(
                f"need >= 2 pages (one is the reserved null page), got "
                f"{n_pages}")
        self.n_pages = n_pages
        # LIFO: freed pages are reused first.
        self._free: List[int] = list(range(n_pages - 1, NULL_PAGE, -1))
        self._ref: Dict[int, int] = {}       # page -> live reference count
        self._cached: Set[int] = set()       # pages the prefix tree holds
        self._tier: Optional[HostTierStore] = None
        self._watermark = 0
        self._allocs = 0
        self._frees = 0
        self._denied = 0

    def attach_tier(self, tier: HostTierStore) -> None:
        """Attach the host tier (kv_tiering engines): its demoted
        partition joins ``assert_consistent`` and its gauges ride
        ``metrics()`` — detached engines publish byte-identical
        expositions to the pre-tiering ones."""
        self._tier = tier

    @property
    def tier(self) -> Optional[HostTierStore]:
        return self._tier

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return len(self._ref)

    def ref(self, page: int) -> int:
        """Live reference count of ``page`` (0 when free)."""
        return self._ref.get(page, 0)

    def alloc(self, n: int,
              count_denied: bool = True) -> Optional[List[int]]:
        """n pages at refcount 1, or None when fewer than n are free
        (all-or-nothing — a partial grant could deadlock two admissions
        against each other). ``count_denied=False`` suppresses the denial
        counter for RETRIES of an already-counted request — the batcher
        re-attempts its blocked queue head every decode step, and counting
        each retry would report a thousand denials for one waiting
        request."""
        if n < 0:
            raise ValueError(f"negative page count {n}")
        if n > len(self._free):
            if count_denied:
                self._denied += 1
            return None
        pages, self._free = self._free[len(self._free) - n:], \
            self._free[:len(self._free) - n]
        pages.reverse()                      # LIFO pop order, stable ids
        for p in pages:
            self._ref[p] = 1
        self._watermark = max(self._watermark, len(self._ref))
        self._allocs += n
        return pages

    def retain(self, pages: Iterable[int]) -> None:
        """Add one reference per page — how a slot's block table comes to
        share a cached prefix page. Validated BEFORE any state mutates:
        retaining a free (or null) page would resurrect a buffer another
        request is about to overwrite."""
        pages = list(pages)
        for p in pages:
            if p == NULL_PAGE:
                raise ValueError("cannot retain the reserved null page")
            if p not in self._ref:
                raise RuntimeError(
                    f"retain of free/foreign page {p}: only allocated "
                    f"pages can gain references")
        for p in pages:
            self._ref[p] += 1

    def free(self, pages: Iterable[int]) -> None:
        """Drop one reference per page; a page returns to the pool when
        its LAST reference drops. Per-page validated BEFORE any state
        mutates: a double free (or freeing a page this allocator never
        handed out) would put the same id on the free list twice, handing
        one physical page to two future requests — silent KV
        cross-contamination, the worst possible failure mode. A page
        whose only remaining reference is the prefix tree's must be
        released via ``drop_cached`` (eviction), never ``free`` — hitting
        that here means slot bookkeeping leaked a tree reference."""
        pages = list(pages)
        for p in pages:
            if p == NULL_PAGE:
                raise ValueError("cannot free the reserved null page")
            if p not in self._ref:
                raise RuntimeError(
                    f"double free (or foreign page): page {p} is not "
                    f"currently allocated")
        drops: Dict[int, int] = {}
        for p in pages:
            drops[p] = drops.get(p, 0) + 1
        for p, n in drops.items():
            if self._ref[p] < n:
                raise RuntimeError(
                    f"double free: page {p} freed {n}x with only "
                    f"{self._ref[p]} live reference(s)")
            if self._ref[p] == n and p in self._cached:
                raise RuntimeError(
                    f"page {p} is still cached by the prefix tree — its "
                    f"tree reference must drop via drop_cached (eviction), "
                    f"not free")
        for p in pages:
            self._ref[p] -= 1
            if self._ref[p] == 0:
                del self._ref[p]
                self._free.append(p)
        self._frees += len(pages)

    def adopt(self, pages: Iterable[int]) -> None:
        """Re-label one existing reference per page as the prefix tree's
        (donation: the reaped slot's reference transfers to the tree, so
        counts don't change — only the ``cached`` partition does)."""
        pages = list(pages)
        for p in pages:
            if p not in self._ref:
                raise RuntimeError(f"adopt of free/foreign page {p}")
            if p in self._cached:
                raise RuntimeError(f"page {p} is already cached")
        self._cached.update(pages)

    def drop_cached(self, page: int) -> None:
        """Eviction: drop the prefix tree's reference on ``page``. The
        page returns to the free list iff no slot still shares it."""
        if page not in self._cached:
            raise RuntimeError(f"page {page} is not cached")
        self._cached.discard(page)
        self.free([page])

    @property
    def cached_pages(self) -> int:
        return len(self._cached)

    def assert_consistent(self) -> None:
        """Invariant check for tests and the bench leg: the free list,
        the slot-held pages and the tree-cached pages PARTITION the usable
        pool — no page in two buckets, none missing, no duplicate free
        entries, no zero/negative refcounts, the null page in none of
        them. Raises RuntimeError on the first violation."""
        usable = set(range(1, self.n_pages))
        free = list(self._free)
        if len(free) != len(set(free)):
            raise RuntimeError(f"free list holds duplicates: {sorted(free)}")
        free_s = set(free)
        held = set(self._ref)
        if NULL_PAGE in free_s or NULL_PAGE in held:
            raise RuntimeError("null page leaked into the pool bookkeeping")
        if free_s & held:
            raise RuntimeError(
                f"pages both free and allocated: {sorted(free_s & held)}")
        if free_s | held != usable:
            raise RuntimeError(
                f"pool not covered: missing {sorted(usable - free_s - held)}"
                f", foreign {sorted((free_s | held) - usable)}")
        bad_refs = {p: c for p, c in self._ref.items() if c < 1}
        if bad_refs:
            raise RuntimeError(f"non-positive refcounts: {bad_refs}")
        if not self._cached <= held:
            raise RuntimeError(
                f"cached pages not allocated: "
                f"{sorted(self._cached - held)}")
        if self._tier is not None:
            # The tier partition (free ∪ held ∪ cached ∪ demoted):
            # demoted pages live in the tier's own key namespace — a
            # PENDING demotion is the only overlap window, and its pool
            # page must still be cached (bytes not yet copied off-pool).
            self._tier.assert_consistent()
            stranded = {k: p for k, p in self._tier._pending.items()
                        if p not in self._cached}
            if stranded:
                raise RuntimeError(
                    f"pending demotions of uncached pages: {stranded} — "
                    f"a demotion enqueued a page the tree no longer "
                    f"holds, so the readback would copy reused bytes")

    def metrics(self) -> Dict[str, float]:
        """Allocator state for the bench/Observation publishers. The
        utilization is instantaneous (pages now referenced / usable pool);
        the watermark is the high-water mark since construction."""
        usable = self.n_pages - 1
        out = {
            "pages_total": float(usable),
            "pages_free": float(len(self._free)),
            "pages_in_use": float(len(self._ref)),
            "pages_cached": float(len(self._cached)),
            "pages_watermark": float(self._watermark),
            "page_allocs": float(self._allocs),
            "page_frees": float(self._frees),
            "page_denied": float(self._denied),
            "page_utilization": (len(self._ref) / usable) if usable else 0.0,
        }
        if self._tier is not None:
            # Tier gauges ride only when tiering is on — detached
            # engines keep the pre-tiering exposition byte-identical.
            out.update(self._tier.metrics())
        return out
