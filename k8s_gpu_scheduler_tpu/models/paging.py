"""Host-side KV page allocator for the paged serving cache.

The paged ContinuousBatcher (models/serving.py) replaces the shared
scalar cursor with a pool of fixed-size KV pages and a per-slot block
table: admission needs FREE PAGES, not a contiguous window, so a prompt
admits the moment enough requests have finished — no backward-write
trick, no epoch roll, no all-slots-drained idle boundary. This module is
the allocator half of that design: a LIFO free list (recently freed
pages are re-written soonest — friendliest to whatever HBM pages are
still warm) with watermark/churn metrics the bench and the serving
entrypoint publish.

Since the prefix cache landed (models/prefix_cache.py) pages are
REF-COUNTED: one physical page can back the block tables of many slots
at once (a shared system-prompt prefix — or, since the decoded-suffix
donation, a whole previous conversation turn: reaped requests donate
their prompt AND resident decoded pages, so multi-turn follow-ups mount
the entire transcript) plus a reference held by the radix tree itself. ``alloc`` hands out pages at refcount 1, ``retain``
adds a holder, ``free`` drops one — a page returns to the free list only
when its LAST reference drops. The tree's reference is labeled via
``adopt``/``drop_cached`` so the pool partitions cleanly into
free / held / cached for the ``assert_consistent`` invariant check.

Page 0 is RESERVED as the null/scratch page: device-side writes for
inactive slots and the over-provisioned tail of a padded prefill scatter
are redirected there (a fixed, never-handed-out target keeps those
writes branch-free on device), and zeroed block-table rows point at it.
Its contents are garbage by design and never attended — every read of it
is masked by the length bound.

Allocation is all-or-nothing and WORST-CASE at admission: the batcher
reserves ceil((prompt + decode rows)/page_size) pages up front, so a
request in flight can never stall mid-decode waiting for a page another
stuck request holds (no allocation deadlock), at the cost of eos
early-stop releasing its unused tail only at finish. In SPECULATIVE mode
the decode-row term grows by gamma (serving.ContinuousBatcher
._rows_needed): every verify dispatch writes the full 1+gamma window but
commits only the accepted prefix, so up to gamma rejected rows overshoot
the committed ``lens`` — the reservation guarantees those rows land in
pages THIS slot already owns, which is why rewind is a pure lens clamp:
no page changes hands, no shared (prefix-cache) page is ever written,
and the overshoot pages return through the ordinary ``free`` at finish
like any reservation slack. Free is immediate and exact — the
fragmentation the contiguous cursor design pays (stale epochs,
bucket-ladder re-dispatch, roll stalls) simply has no analog here.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set

NULL_PAGE = 0


class PageAllocator:
    """Fixed-size page pool bookkeeping. ``n_pages`` counts the WHOLE pool
    including the reserved null page, so a pool of n_pages has
    ``n_pages - 1`` usable pages."""

    def __init__(self, n_pages: int) -> None:
        if n_pages < 2:
            raise ValueError(
                f"need >= 2 pages (one is the reserved null page), got "
                f"{n_pages}")
        self.n_pages = n_pages
        # LIFO: freed pages are reused first.
        self._free: List[int] = list(range(n_pages - 1, NULL_PAGE, -1))
        self._ref: Dict[int, int] = {}       # page -> live reference count
        self._cached: Set[int] = set()       # pages the prefix tree holds
        self._watermark = 0
        self._allocs = 0
        self._frees = 0
        self._denied = 0

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return len(self._ref)

    def ref(self, page: int) -> int:
        """Live reference count of ``page`` (0 when free)."""
        return self._ref.get(page, 0)

    def alloc(self, n: int,
              count_denied: bool = True) -> Optional[List[int]]:
        """n pages at refcount 1, or None when fewer than n are free
        (all-or-nothing — a partial grant could deadlock two admissions
        against each other). ``count_denied=False`` suppresses the denial
        counter for RETRIES of an already-counted request — the batcher
        re-attempts its blocked queue head every decode step, and counting
        each retry would report a thousand denials for one waiting
        request."""
        if n < 0:
            raise ValueError(f"negative page count {n}")
        if n > len(self._free):
            if count_denied:
                self._denied += 1
            return None
        pages, self._free = self._free[len(self._free) - n:], \
            self._free[:len(self._free) - n]
        pages.reverse()                      # LIFO pop order, stable ids
        for p in pages:
            self._ref[p] = 1
        self._watermark = max(self._watermark, len(self._ref))
        self._allocs += n
        return pages

    def retain(self, pages: Iterable[int]) -> None:
        """Add one reference per page — how a slot's block table comes to
        share a cached prefix page. Validated BEFORE any state mutates:
        retaining a free (or null) page would resurrect a buffer another
        request is about to overwrite."""
        pages = list(pages)
        for p in pages:
            if p == NULL_PAGE:
                raise ValueError("cannot retain the reserved null page")
            if p not in self._ref:
                raise RuntimeError(
                    f"retain of free/foreign page {p}: only allocated "
                    f"pages can gain references")
        for p in pages:
            self._ref[p] += 1

    def free(self, pages: Iterable[int]) -> None:
        """Drop one reference per page; a page returns to the pool when
        its LAST reference drops. Per-page validated BEFORE any state
        mutates: a double free (or freeing a page this allocator never
        handed out) would put the same id on the free list twice, handing
        one physical page to two future requests — silent KV
        cross-contamination, the worst possible failure mode. A page
        whose only remaining reference is the prefix tree's must be
        released via ``drop_cached`` (eviction), never ``free`` — hitting
        that here means slot bookkeeping leaked a tree reference."""
        pages = list(pages)
        for p in pages:
            if p == NULL_PAGE:
                raise ValueError("cannot free the reserved null page")
            if p not in self._ref:
                raise RuntimeError(
                    f"double free (or foreign page): page {p} is not "
                    f"currently allocated")
        drops: Dict[int, int] = {}
        for p in pages:
            drops[p] = drops.get(p, 0) + 1
        for p, n in drops.items():
            if self._ref[p] < n:
                raise RuntimeError(
                    f"double free: page {p} freed {n}x with only "
                    f"{self._ref[p]} live reference(s)")
            if self._ref[p] == n and p in self._cached:
                raise RuntimeError(
                    f"page {p} is still cached by the prefix tree — its "
                    f"tree reference must drop via drop_cached (eviction), "
                    f"not free")
        for p in pages:
            self._ref[p] -= 1
            if self._ref[p] == 0:
                del self._ref[p]
                self._free.append(p)
        self._frees += len(pages)

    def adopt(self, pages: Iterable[int]) -> None:
        """Re-label one existing reference per page as the prefix tree's
        (donation: the reaped slot's reference transfers to the tree, so
        counts don't change — only the ``cached`` partition does)."""
        pages = list(pages)
        for p in pages:
            if p not in self._ref:
                raise RuntimeError(f"adopt of free/foreign page {p}")
            if p in self._cached:
                raise RuntimeError(f"page {p} is already cached")
        self._cached.update(pages)

    def drop_cached(self, page: int) -> None:
        """Eviction: drop the prefix tree's reference on ``page``. The
        page returns to the free list iff no slot still shares it."""
        if page not in self._cached:
            raise RuntimeError(f"page {page} is not cached")
        self._cached.discard(page)
        self.free([page])

    @property
    def cached_pages(self) -> int:
        return len(self._cached)

    def assert_consistent(self) -> None:
        """Invariant check for tests and the bench leg: the free list,
        the slot-held pages and the tree-cached pages PARTITION the usable
        pool — no page in two buckets, none missing, no duplicate free
        entries, no zero/negative refcounts, the null page in none of
        them. Raises RuntimeError on the first violation."""
        usable = set(range(1, self.n_pages))
        free = list(self._free)
        if len(free) != len(set(free)):
            raise RuntimeError(f"free list holds duplicates: {sorted(free)}")
        free_s = set(free)
        held = set(self._ref)
        if NULL_PAGE in free_s or NULL_PAGE in held:
            raise RuntimeError("null page leaked into the pool bookkeeping")
        if free_s & held:
            raise RuntimeError(
                f"pages both free and allocated: {sorted(free_s & held)}")
        if free_s | held != usable:
            raise RuntimeError(
                f"pool not covered: missing {sorted(usable - free_s - held)}"
                f", foreign {sorted((free_s | held) - usable)}")
        bad_refs = {p: c for p, c in self._ref.items() if c < 1}
        if bad_refs:
            raise RuntimeError(f"non-positive refcounts: {bad_refs}")
        if not self._cached <= held:
            raise RuntimeError(
                f"cached pages not allocated: "
                f"{sorted(self._cached - held)}")

    def metrics(self) -> Dict[str, float]:
        """Allocator state for the bench/Observation publishers. The
        utilization is instantaneous (pages now referenced / usable pool);
        the watermark is the high-water mark since construction."""
        usable = self.n_pages - 1
        return {
            "pages_total": float(usable),
            "pages_free": float(len(self._free)),
            "pages_in_use": float(len(self._ref)),
            "pages_cached": float(len(self._cached)),
            "pages_watermark": float(self._watermark),
            "page_allocs": float(self._allocs),
            "page_frees": float(self._frees),
            "page_denied": float(self._denied),
            "page_utilization": (len(self._ref) / usable) if usable else 0.0,
        }
