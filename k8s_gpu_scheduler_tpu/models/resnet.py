"""ResNet-50 — the single-chip training workload (BASELINE config 2).

Pure-JAX bottleneck ResNet with a stacked/scanned layer scheme like the
decoder: stages carry (conv weights, batch-norm scale/bias) pytrees and the
forward is NHWC convolutions — the MXU-friendly layout on TPU (lax conv with
feature-last avoids transposes). BatchNorm runs in inference-style
normalization with learned scale/bias plus batch statistics during training
(simple, jit-stable: no running-average state threading).
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: tuple = (3, 4, 6, 3)  # ResNet-50
    width: int = 64
    n_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @staticmethod
    def resnet50() -> "ResNetConfig":
        return ResNetConfig()

    @staticmethod
    def tiny() -> "ResNetConfig":
        return ResNetConfig(stage_sizes=(1, 1), width=8, n_classes=10)


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _bn(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean((0, 1, 2), keepdims=True)
    var = x32.var((0, 1, 2), keepdims=True)
    return (((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale + bias)


def init_params(cfg: ResNetConfig, key: jax.Array) -> Dict:
    keys = iter(jax.random.split(key, 256))

    def conv_w(k, kh, kw, cin, cout):
        fan_in = kh * kw * cin
        return (jax.random.normal(k, (kh, kw, cin, cout), jnp.float32)
                * (2.0 / fan_in) ** 0.5).astype(cfg.dtype)

    def bn_p(c):
        return {"scale": jnp.ones((c,), cfg.dtype), "bias": jnp.zeros((c,), cfg.dtype)}

    params: Dict = {
        "stem": {"conv": conv_w(next(keys), 7, 7, 3, cfg.width), **bn_p(cfg.width)},
        "stages": [],
    }
    cin = cfg.width
    for s, n_blocks in enumerate(cfg.stage_sizes):
        cmid = cfg.width * (2 ** s)
        cout = cmid * 4
        blocks: List[Dict] = []
        for b in range(n_blocks):
            blocks.append({
                "c1": conv_w(next(keys), 1, 1, cin, cmid), "b1": bn_p(cmid),
                "c2": conv_w(next(keys), 3, 3, cmid, cmid), "b2": bn_p(cmid),
                "c3": conv_w(next(keys), 1, 1, cmid, cout), "b3": bn_p(cout),
                "proj": (conv_w(next(keys), 1, 1, cin, cout)
                         if (b == 0) else jnp.zeros((0,), cfg.dtype)),
                "bproj": bn_p(cout) if b == 0 else {"scale": jnp.zeros((0,)),
                                                    "bias": jnp.zeros((0,))},
            })
            cin = cout
        params["stages"].append(blocks)
    params["head"] = (jax.random.normal(next(keys), (cin, cfg.n_classes),
                                        jnp.float32) * 0.01).astype(cfg.dtype)
    return params


def forward(params: Dict, images: jax.Array, cfg: ResNetConfig) -> jax.Array:
    """images [B, H, W, 3] → logits [B, n_classes]."""
    x = images.astype(cfg.dtype)
    x = _bn(_conv(x, params["stem"]["conv"], stride=2),
            params["stem"]["scale"], params["stem"]["bias"])
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")
    for s, blocks in enumerate(params["stages"]):
        for b, blk in enumerate(blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            h = jax.nn.relu(_bn(_conv(x, blk["c1"]), **blk["b1"]))
            h = jax.nn.relu(_bn(_conv(h, blk["c2"], stride=stride), **blk["b2"]))
            h = _bn(_conv(h, blk["c3"]), **blk["b3"])
            if blk["proj"].size:
                x = _bn(_conv(x, blk["proj"], stride=stride), **blk["bproj"])
            x = jax.nn.relu(x + h)
    x = x.mean(axis=(1, 2))
    return (x @ params["head"]).astype(jnp.float32)


def loss_fn(params: Dict, batch: Dict, cfg: ResNetConfig) -> jax.Array:
    logits = forward(params, batch["images"], cfg)
    logp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(logp, batch["labels"][:, None], axis=1).mean()


def make_train_step(cfg: ResNetConfig, optimizer):
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, cfg)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u.astype(p.dtype), params, updates)
        return params, opt_state, loss

    return jax.jit(step)


def main() -> None:  # pragma: no cover — the deploy/workloads entrypoint
    import os
    import time

    import optax

    from ..utils.enforcement import apply_env_limits

    throttle = apply_env_limits()   # HBM cap + duty pacing (scheduler env)
    cfg = ResNetConfig.resnet50()
    params = init_params(cfg, jax.random.PRNGKey(0))
    B = 64
    batch = {
        "images": jax.random.normal(jax.random.PRNGKey(1), (B, 224, 224, 3)),
        "labels": jax.random.randint(jax.random.PRNGKey(2), (B,), 0, cfg.n_classes),
    }
    opt = optax.sgd(0.1, momentum=0.9)
    state = opt.init(params)
    step = make_train_step(cfg, opt)
    params, state, loss = step(params, state, batch)  # compile
    float(loss)
    slo = float(os.environ.get("SLO", "0") or 0)
    from ..recommender.collector import make_workload_publisher

    publish = make_workload_publisher()
    last_pub = 0.0
    while True:
        t0 = time.perf_counter()
        params, state, loss = step(params, state, batch)
        float(loss)
        step_dt = time.perf_counter() - t0
        ips = B / step_dt
        if throttle is not None:
            throttle.pace(step_dt)
        print(f"resnet50 img/s={ips:.1f} loss={float(loss):.3f} slo={slo} "
              f"chips={os.environ.get('TPU_VISIBLE_CHIPS', '?')}", flush=True)
        # Feedback loop (recommender/collector.py), paced to ~1 Hz so a
        # fast step can't hammer the registry. Monotonic pacing: a wall
        # clock step must not silence (or burst) the publish cadence.
        if publish is not None and time.monotonic() - last_pub >= 1.0:
            publish(ips)
            last_pub = time.monotonic()


if __name__ == "__main__":  # pragma: no cover
    main()
