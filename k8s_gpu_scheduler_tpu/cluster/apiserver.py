"""Hermetic in-memory API server with watch streams.

The reference leans on a live kube-apiserver through client-go (informers at
gpu_plugins.go:785-796, CRUD at pkg/resources/). Its tests therefore need the
author's real cluster (SURVEY.md §4 — pods_test.go reads
/home/dimitris/.kube/config). We instead make the API server a first-class,
in-process component: every layer above it (informers, scheduler, agents)
sees list/watch semantics identical to Kubernetes', and the whole framework
is testable on a laptop. A REST shim can later front a real apiserver with
the same interface.

Concurrency: one mutex guards the store; watch delivery is out-of-line via
per-subscriber queues so a slow consumer never blocks a writer (the
reference's analogous hazard — package-level informer globals mutated from
concurrent Score calls, gpu_plugins.go:46-81 — is designed away here).
"""
from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ..api.objects import deepcopy_obj, kind_of


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    obj: Any


class Conflict(Exception):
    pass


class NotFound(Exception):
    pass


class AlreadyExists(Exception):
    pass


class APIServer:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        # kind -> "ns/name" -> object
        self._store: Dict[str, Dict[str, Any]] = {}
        self._rv = 0
        self._watchers: Dict[str, List[queue.Queue]] = {}

    # -- helpers -----------------------------------------------------------
    def _bump_locked(self, obj: Any) -> None:
        self._rv += 1
        obj.metadata.resource_version = self._rv

    def _notify_locked(self, kind: str, ev: WatchEvent) -> None:
        for q in self._watchers.get(kind, []):
            q.put(ev)

    # -- CRUD --------------------------------------------------------------
    def create(self, obj: Any) -> Any:
        kind = kind_of(obj)
        obj = deepcopy_obj(obj)
        with self._mu:
            bucket = self._store.setdefault(kind, {})
            key = obj.metadata.key
            if key in bucket:
                raise AlreadyExists(f"{kind} {key}")
            self._bump_locked(obj)
            bucket[key] = obj
            self._notify_locked(kind, WatchEvent("ADDED", deepcopy_obj(obj)))
        return deepcopy_obj(obj)

    def get(self, kind: str, name: str, namespace: str = "default") -> Any:
        with self._mu:
            obj = self._store.get(kind, {}).get(f"{namespace}/{name}")
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            return deepcopy_obj(obj)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        field_fn: Optional[Callable[[Any], bool]] = None,
    ) -> List[Any]:
        with self._mu:
            out = []
            for key, obj in self._store.get(kind, {}).items():
                if namespace is not None and obj.metadata.namespace != namespace:
                    continue
                if label_selector and any(
                    obj.metadata.labels.get(k) != v for k, v in label_selector.items()
                ):
                    continue
                if field_fn is not None and not field_fn(obj):
                    continue
                out.append(deepcopy_obj(obj))
            return out

    def update(self, obj: Any, expect_rv: Optional[int] = None) -> Any:
        """Replace; optimistic concurrency when expect_rv given."""
        kind = kind_of(obj)
        obj = deepcopy_obj(obj)
        with self._mu:
            bucket = self._store.setdefault(kind, {})
            key = obj.metadata.key
            cur = bucket.get(key)
            if cur is None:
                raise NotFound(f"{kind} {key}")
            if expect_rv is not None and cur.metadata.resource_version != expect_rv:
                raise Conflict(
                    f"{kind} {key}: rv {cur.metadata.resource_version} != {expect_rv}"
                )
            self._bump_locked(obj)
            bucket[key] = obj
            self._notify_locked(kind, WatchEvent("MODIFIED", deepcopy_obj(obj)))
        return deepcopy_obj(obj)

    def mutate(self, kind: str, name: str, namespace: str, fn: Callable[[Any], None]) -> Any:
        """Atomic read-modify-write under the store lock — the primitive the
        scheduler uses for ConfigMap appends (the reference's racy
        read-then-Update at pkg/resources/pods.go:156-175 becomes atomic)."""
        with self._mu:
            cur = self._store.get(kind, {}).get(f"{namespace}/{name}")
            if cur is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            # fn runs on a copy: a raising fn leaves the store untouched. The
            # stored object is a further copy, so even a fn that retains its
            # argument can never reach live store state afterwards.
            obj = deepcopy_obj(cur)
            fn(obj)
            stored = deepcopy_obj(obj)
            self._bump_locked(stored)
            self._store[kind][f"{namespace}/{name}"] = stored
            self._notify_locked(kind, WatchEvent("MODIFIED", deepcopy_obj(stored)))
            return deepcopy_obj(stored)

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        with self._mu:
            bucket = self._store.get(kind, {})
            key = f"{namespace}/{name}"
            obj = bucket.pop(key, None)
            if obj is None:
                raise NotFound(f"{kind} {key}")
            self._notify_locked(kind, WatchEvent("DELETED", deepcopy_obj(obj)))

    # -- watch -------------------------------------------------------------
    def watch(self, kind: str, send_initial: bool = True) -> "Watch":
        q: queue.Queue = queue.Queue()
        with self._mu:
            if send_initial:
                for obj in self._store.get(kind, {}).values():
                    q.put(WatchEvent("ADDED", deepcopy_obj(obj)))
            self._watchers.setdefault(kind, []).append(q)
        return Watch(self, kind, q)

    def _unwatch(self, kind: str, q: queue.Queue) -> None:
        with self._mu:
            try:
                self._watchers.get(kind, []).remove(q)
            except ValueError:
                pass


class Watch:
    """Iterable watch stream; ``stop()`` to unsubscribe."""

    _SENTINEL = object()

    def __init__(self, server: APIServer, kind: str, q: queue.Queue) -> None:
        self._server = server
        self._kind = kind
        self._q = q
        self._stopped = False

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if ev is Watch._SENTINEL:
            return None
        return ev

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self._server._unwatch(self._kind, self._q)
            self._q.put(Watch._SENTINEL)
