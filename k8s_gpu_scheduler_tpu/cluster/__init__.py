from .apiserver import APIServer, WatchEvent  # noqa: F401
from .informers import SharedInformerFactory  # noqa: F401
from .resources import Descriptor, PatchNodeParam  # noqa: F401
