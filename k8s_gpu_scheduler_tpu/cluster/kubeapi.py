"""Kubernetes REST adapter — APIServer-compatible client for real clusters.

The reference talks to kube-apiserver through client-go; our control plane
talks to the ``cluster.APIServer`` interface (create/get/list/mutate/delete/
watch). This module implements that interface over the Kubernetes REST API
with nothing but the standard library, so the SAME Scheduler/informers/
plugins run unchanged in-cluster (``cmd.scheduler --in-cluster``):

- auth: in-cluster service-account token + CA
  (/var/run/secrets/kubernetes.io/serviceaccount) or explicit
  ``base_url``/``token`` (tests drive a fake HTTP apiserver);
- objects: k8s JSON ↔ the typed model in api/objects.py (Pod, Node,
  ConfigMap, and the PodGroup CRD at scheduling.tpu.dev/v1);
- watch: chunked streaming GET (?watch=1&resourceVersion=N) per kind, one
  reader thread feeding the same Watch queue contract the informers expect;
- binding: setting spec.nodeName is rejected by a real apiserver, so
  ``mutate`` detects the bind pattern and POSTs a Binding subresource
  instead (what kube-scheduler itself does).
"""
from __future__ import annotations

import http.client
import json
import logging
import queue
import socket
import ssl
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from ..api.objects import (
    ConfigMap,
    ConfigMapRef,
    Container,
    EnvVar,
    Lease,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodGroup,
    PodSpec,
    PodStatus,
    ResourceRequirements,
)
from .apiserver import AlreadyExists, Conflict, NotFound, WatchEvent

log = logging.getLogger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class StatusError(RuntimeError):
    """Non-404/409 HTTP failure, carrying the status code so callers can
    react to specific ones (410 Gone → watch re-list)."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code

# kind -> (api prefix, plural, namespaced)
_ROUTES = {
    "Pod": ("/api/v1", "pods", True),
    "Node": ("/api/v1", "nodes", False),
    "ConfigMap": ("/api/v1", "configmaps", True),
    "PodGroup": ("/apis/scheduling.tpu.dev/v1", "podgroups", True),
    "Lease": ("/apis/coordination.k8s.io/v1", "leases", True),
}


# -- JSON ↔ typed objects -----------------------------------------------------

def _meta_from(d: Dict) -> ObjectMeta:
    rv = d.get("resourceVersion", 0)
    return ObjectMeta(
        name=d.get("name", ""),
        namespace=d.get("namespace", "default"),
        labels=d.get("labels") or {},
        annotations=d.get("annotations") or {},
        uid=d.get("uid") or d.get("name", ""),
        resource_version=int(rv) if str(rv).isdigit() else 0,
        owner_references=[
            f"{r.get('kind', '')}/{r.get('name', '')}"
            for r in d.get("ownerReferences") or []
        ],
    )


def _meta_to(m: ObjectMeta, namespaced: bool) -> Dict:
    out: Dict[str, Any] = {"name": m.name, "labels": m.labels,
                           "annotations": m.annotations}
    if namespaced:
        out["namespace"] = m.namespace
    if m.owner_references:
        # Inverse of _meta_from's "Kind/name" flattening — without this a
        # pod CREATED through this adapter silently loses its controller
        # reference, and both preemption victim eligibility and the gang
        # bare-pod eviction guard key on having one. The model keeps only
        # kind+name, so the emitted refs are the create-side minimum
        # (apiVersion inferred for the common controller kinds); callers
        # that PATCH must strip the key (see mutate) — merge-patch would
        # REPLACE a real apiserver's full refs (uid, controller flags)
        # with this reduced form.
        api_of = {"StatefulSet": "apps/v1", "Deployment": "apps/v1",
                  "ReplicaSet": "apps/v1", "DaemonSet": "apps/v1",
                  "Job": "batch/v1"}
        out["ownerReferences"] = [
            {"apiVersion": api_of.get(r.split("/", 1)[0], "v1"),
             "kind": r.split("/", 1)[0], "name": r.split("/", 1)[-1]}
            for r in m.owner_references
        ]
    return out


def _quantity(v) -> float:
    """k8s quantity → float (chips are integers; tolerate '4' and 4)."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return 0.0


def pod_from_json(d: Dict) -> Pod:
    spec = d.get("spec") or {}
    containers = []
    for c in spec.get("containers", []):
        res = c.get("resources") or {}
        containers.append(Container(
            name=c.get("name", "main"),
            image=c.get("image", ""),
            env=[EnvVar(e["name"], e.get("value", ""))
                 for e in c.get("env", []) if "name" in e],
            env_from=[ConfigMapRef(ref["configMapRef"]["name"])
                      for ref in c.get("envFrom", []) if "configMapRef" in ref],
            resources=ResourceRequirements(
                requests={k: _quantity(v)
                          for k, v in (res.get("requests") or {}).items()},
                limits={k: _quantity(v)
                        for k, v in (res.get("limits") or {}).items()},
            ),
        ))
    status = d.get("status") or {}
    return Pod(
        metadata=_meta_from(d.get("metadata") or {}),
        spec=PodSpec(
            containers=containers,
            node_name=spec.get("nodeName", ""),
            scheduler_name=spec.get("schedulerName", "default-scheduler"),
            node_selector=spec.get("nodeSelector") or {},
            hostname=spec.get("hostname", ""),
            subdomain=spec.get("subdomain", ""),
        ),
        status=PodStatus(
            phase=status.get("phase", "Pending"),
            host_ip=status.get("hostIP", ""),
            pod_ip=status.get("podIP", ""),
        ),
    )


def node_from_json(d: Dict) -> Node:
    status = d.get("status") or {}
    raw_conditions = status.get("conditions", [])
    conditions = [c.get("type", "") for c in raw_conditions
                  if c.get("status") == "True"]
    # Only default to Ready when the node reports NO conditions at all
    # (fake/test servers). A real NotReady node (conditions present, none
    # True) must map to an empty list so the plugin's readiness filter
    # fires — defaulting it to Ready would bind pods to dead nodes.
    if not raw_conditions:
        conditions = ["Ready"]
    addresses = [a.get("address", "") for a in status.get("addresses", [])]
    return Node(
        metadata=_meta_from(d.get("metadata") or {}),
        status=NodeStatus(
            capacity={k: _quantity(v)
                      for k, v in (status.get("capacity") or {}).items()},
            allocatable={k: _quantity(v)
                         for k, v in (status.get("allocatable") or {}).items()},
            addresses=addresses,
            conditions=conditions,
        ),
    )


def configmap_from_json(d: Dict) -> ConfigMap:
    return ConfigMap(metadata=_meta_from(d.get("metadata") or {}),
                     data=dict(d.get("data") or {}))


def _rfc3339(epoch: float) -> Optional[str]:
    if not epoch:
        return None
    import datetime as _dt

    return _dt.datetime.fromtimestamp(
        epoch, _dt.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


def _from_rfc3339(s: Optional[str]) -> float:
    if not s:
        return 0.0
    import datetime as _dt

    try:
        return _dt.datetime.strptime(
            s.replace("Z", "+0000"), "%Y-%m-%dT%H:%M:%S.%f%z").timestamp()
    except ValueError:
        try:
            return _dt.datetime.strptime(
                s.replace("Z", "+0000"), "%Y-%m-%dT%H:%M:%S%z").timestamp()
        except ValueError:
            return 0.0


def lease_from_json(d: Dict) -> Lease:
    spec = d.get("spec") or {}
    return Lease(
        metadata=_meta_from(d.get("metadata") or {}),
        holder_identity=spec.get("holderIdentity") or "",
        lease_duration_s=float(spec.get("leaseDurationSeconds", 15)),
        acquire_time=_from_rfc3339(spec.get("acquireTime")),
        renew_time=_from_rfc3339(spec.get("renewTime")),
        lease_transitions=int(spec.get("leaseTransitions", 0)),
    )


def podgroup_from_json(d: Dict) -> PodGroup:
    spec = d.get("spec") or {}
    return PodGroup(
        metadata=_meta_from(d.get("metadata") or {}),
        min_member=int(spec.get("minMember", 1)),
        topology=spec.get("topology", ""),
        schedule_timeout_s=float(spec.get("scheduleTimeoutSeconds", 60)),
    )


def obj_to_json(obj: Any) -> Dict:
    kind = obj.kind
    _, _, namespaced = _ROUTES[kind]
    meta = _meta_to(obj.metadata, namespaced)
    if kind == "Pod":
        return {
            "apiVersion": "v1", "kind": "Pod", "metadata": meta,
            "spec": {
                "schedulerName": obj.spec.scheduler_name,
                "nodeName": obj.spec.node_name or None,
                "nodeSelector": obj.spec.node_selector,
                "hostname": obj.spec.hostname or None,
                "subdomain": obj.spec.subdomain or None,
                "containers": [{
                    "name": c.name, "image": c.image,
                    "env": [{"name": e.name, "value": e.value} for e in c.env],
                    "envFrom": [{"configMapRef": {"name": r.name}}
                                for r in c.env_from],
                    "resources": {
                        "requests": {k: str(int(v)) for k, v in
                                     c.resources.requests.items()},
                        "limits": {k: str(int(v)) for k, v in
                                   c.resources.limits.items()},
                    },
                } for c in obj.spec.containers],
            },
        }
    if kind == "ConfigMap":
        return {"apiVersion": "v1", "kind": "ConfigMap", "metadata": meta,
                "data": obj.data}
    if kind == "Node":
        return {"apiVersion": "v1", "kind": "Node", "metadata": meta}
    if kind == "PodGroup":
        return {
            "apiVersion": "scheduling.tpu.dev/v1", "kind": "PodGroup",
            "metadata": meta,
            "spec": {"minMember": obj.min_member, "topology": obj.topology,
                     "scheduleTimeoutSeconds": int(obj.schedule_timeout_s)},
        }
    if kind == "Lease":
        return {
            "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": meta,
            "spec": {
                "holderIdentity": obj.holder_identity or None,
                "leaseDurationSeconds": int(obj.lease_duration_s),
                "acquireTime": _rfc3339(obj.acquire_time),
                "renewTime": _rfc3339(obj.renew_time),
                "leaseTransitions": obj.lease_transitions,
            },
        }
    raise TypeError(f"unsupported kind {kind}")


_FROM_JSON = {
    "Pod": pod_from_json,
    "Node": node_from_json,
    "ConfigMap": configmap_from_json,
    "PodGroup": podgroup_from_json,
    "Lease": lease_from_json,
}


# -- the adapter --------------------------------------------------------------

class KubeAPIServer:
    """Speaks kube REST; quacks like cluster.APIServer."""

    def __init__(self, base_url: Optional[str] = None,
                 token: Optional[str] = None,
                 ca_file: Optional[str] = None,
                 timeout_s: float = 10.0):
        if base_url is None:
            import os

            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not in-cluster: KUBERNETES_SERVICE_HOST unset "
                    "(pass base_url explicitly)"
                )
            base_url = f"https://{host}:{port}"
            token = token or open(f"{SA_DIR}/token").read().strip()
            ca_file = ca_file or f"{SA_DIR}/ca.crt"
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout_s = timeout_s
        self._ctx: Optional[ssl.SSLContext] = None
        if self.base_url.startswith("https"):
            self._ctx = ssl.create_default_context(
                cafile=ca_file) if ca_file else ssl.create_default_context()
        # One persistent keep-alive connection per thread (client-go reuses
        # HTTP/2 streams the same way). A fresh TCP+TLS handshake per
        # request is not just slow — under a burst of concurrent binds the
        # connection storm overflows the apiserver's accept backlog and
        # dropped SYNs stall individual requests for the full 1 s
        # retransmit timeout (measured against the bench fake).
        self._local = threading.local()
        # Path prefix of base_url (proxied apiservers like
        # https://gw.example/k8s) — http.client takes host/port only, so
        # the prefix must be re-applied per request or pooled calls would
        # silently hit the wrong URL while watches (urllib) work.
        self._base_path = urllib.parse.urlsplit(self.base_url).path.rstrip("/")

    # -- HTTP plumbing -----------------------------------------------------
    def _new_conn(self):
        u = urllib.parse.urlsplit(self.base_url)
        if u.scheme == "https":
            conn = http.client.HTTPSConnection(
                u.hostname, u.port or 443, timeout=self.timeout_s,
                context=self._ctx)
        else:
            conn = http.client.HTTPConnection(
                u.hostname, u.port or 80, timeout=self.timeout_s)
        # TCP_NODELAY: on a reused keep-alive connection, Nagle holds the
        # request's second segment until the server's delayed ACK —
        # a constant ~100 ms floor per request (measured). Real apiserver
        # clients disable Nagle for exactly this reason.
        conn.connect()
        try:
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except (OSError, AttributeError):
            pass
        return conn

    def _request(self, method: str, path: str, body: Optional[Dict] = None,
                 content_type: str = "application/json", stream: bool = False):
        headers = {}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        data = json.dumps(body).encode() if body is not None else None
        if data is not None:
            headers["Content-Type"] = content_type

        if stream:
            # Watches hold their connection for the stream's lifetime —
            # never pooled; urllib's per-call connection is the right shape.
            req = urllib.request.Request(
                self.base_url + path, data=data, method=method, headers=headers)
            try:
                return urllib.request.urlopen(req, timeout=None, context=self._ctx)
            except urllib.error.HTTPError as e:
                detail = e.read().decode(errors="replace")[:300]
                raise self._status_error(method, path, e.code, detail) from e

        full_path = self._base_path + path
        for attempt in (0, 1):
            conn = getattr(self._local, "conn", None)
            reused = conn is not None
            if conn is None:
                conn = self._new_conn()
                self._local.conn = conn
            sent = False
            try:
                conn.request(method, full_path, body=data, headers=headers)
                sent = True
                resp = conn.getresponse()
                payload = resp.read()
                break
            except socket.timeout:
                # The server may have APPLIED the request and only the
                # response is late — re-sending a non-idempotent verb
                # (bind POST, create) could double-apply. Surface it.
                self._local.conn = None
                conn.close()
                raise
            except (http.client.HTTPException, ConnectionError, OSError):
                self._local.conn = None
                try:
                    conn.close()
                except Exception:  # noqa: BLE001
                    pass
                # Retry ONLY when re-sending cannot double-apply: the send
                # itself failed (an incomplete request is never processed),
                # or the verb is idempotent and the reused keep-alive died
                # in the response phase. PATCH counts as idempotent here:
                # every merge-patch this client issues sets absolute values
                # (no increments), so replaying one is a no-op. POST (bind,
                # create) that was fully sent may have been applied even
                # though the connection then broke; re-sending it could
                # double-apply, so surface the error instead.
                idempotent = method in ("GET", "HEAD", "PUT", "PATCH", "DELETE")
                if attempt or (sent and not (reused and idempotent)):
                    raise
        if resp.status >= 400:
            detail = payload.decode(errors="replace")[:300]
            raise self._status_error(method, path, resp.status, detail)
        return json.loads(payload or b"{}")

    @staticmethod
    def _status_error(method: str, path: str, code: int, detail: str):
        if code == 404:
            return NotFound(f"{method} {path}: {detail}")
        if code == 409:
            if "AlreadyExists" in detail or method == "POST":
                return AlreadyExists(detail)
            return Conflict(detail)
        return StatusError(code, f"{method} {path} -> {code}: {detail}")

    def _path(self, kind: str, namespace: Optional[str] = None,
              name: Optional[str] = None, suffix: str = "") -> str:
        prefix, plural, namespaced = _ROUTES[kind]
        parts = [prefix]
        if namespaced and namespace is not None:
            parts.append(f"namespaces/{namespace}")
        parts.append(plural)
        if name:
            parts.append(name)
        path = "/".join(parts)
        return path + suffix

    # -- APIServer interface ----------------------------------------------
    def create(self, obj: Any) -> Any:
        kind = obj.kind
        _, _, namespaced = _ROUTES[kind]
        ns = obj.metadata.namespace if namespaced else None
        doc = self._request("POST", self._path(kind, ns), obj_to_json(obj))
        return _FROM_JSON[kind](doc)

    def get(self, kind: str, name: str, namespace: str = "default") -> Any:
        doc = self._request("GET", self._path(kind, namespace, name))
        return _FROM_JSON[kind](doc)

    def list(self, kind: str, namespace: Optional[str] = None,
             label_fn: Optional[Callable] = None,
             field_fn: Optional[Callable] = None) -> List[Any]:
        # all-namespaces list (the informer's view, like client-go factories)
        doc = self._request("GET", self._path(kind, namespace))
        objs = [_FROM_JSON[kind](item) for item in doc.get("items", [])]
        if label_fn:
            objs = [o for o in objs if label_fn(o.metadata.labels)]
        if field_fn:
            objs = [o for o in objs if field_fn(o)]
        return objs

    def mutate(self, kind: str, name: str, namespace: str,
               fn: Callable[[Any], None]) -> Any:
        current = self.get(kind, name, namespace)
        before_node = getattr(getattr(current, "spec", None), "node_name", None)
        # Snapshot the mutable maps BEFORE fn: RFC-7386 merge-patch leaves
        # absent keys untouched, so keys fn() deletes must be sent as
        # explicit nulls or a real apiserver never removes them (the
        # reshaper pops its state annotation this way — without nulls the
        # node would stay filtered as "repartition in progress" forever).
        before = {
            "labels": dict(current.metadata.labels),
            "annotations": dict(current.metadata.annotations),
            "data": dict(current.data) if kind == "ConfigMap" else {},
        }
        fn(current)
        after_node = getattr(getattr(current, "spec", None), "node_name", None)
        if kind == "Pod" and not before_node and after_node:
            # bind: POST the Binding subresource (spec.nodeName is immutable
            # through PATCH on a real apiserver).
            self._request(
                "POST", self._path("Pod", namespace, name, "/binding"),
                {"apiVersion": "v1", "kind": "Binding",
                 "metadata": {"name": name},
                 "target": {"apiVersion": "v1", "kind": "Node",
                            "name": after_node}},
            )
            return current
        body = obj_to_json(current)
        # ownerReferences are read-only through this adapter: a merge-PATCH
        # carrying the model's reduced kind/name form would REPLACE the
        # apiserver's full refs (uid, controller, blockOwnerDeletion) and
        # break garbage collection — and on a strict server 422 for the
        # missing uid. Omitting the key leaves the server's refs untouched.
        body.get("metadata", {}).pop("ownerReferences", None)
        if kind == "Node":
            # only metadata is ours to change on nodes (labels/annotations)
            body = {"metadata": body["metadata"]}
        for field, prev in (("labels", before["labels"]),
                            ("annotations", before["annotations"])):
            removed = set(prev) - set(getattr(current.metadata, field))
            if removed:
                body["metadata"][field] = {**body["metadata"].get(field, {}),
                                           **{k: None for k in removed}}
        if kind == "ConfigMap":
            removed = set(before["data"]) - set(current.data)
            if removed:
                body["data"] = {**body.get("data", {}),
                                **{k: None for k in removed}}
        doc = self._request(
            "PATCH", self._path(kind, namespace, name), body,
            content_type="application/merge-patch+json",
        )
        return _FROM_JSON[kind](doc)

    def bind(self, name: str, namespace: str, node_name: str) -> None:
        """Direct Binding-subresource POST — ONE round trip. The generic
        bind path (mutate) costs a node GET (for host_ip, which a real
        apiserver populates via kubelet anyway) plus a pod GET before the
        POST; on the bind hot path that tripled the HTTP work per pod."""
        self._request(
            "POST", self._path("Pod", namespace, name, "/binding"),
            {"apiVersion": "v1", "kind": "Binding",
             "metadata": {"name": name},
             "target": {"apiVersion": "v1", "kind": "Node",
                        "name": node_name}},
        )

    def patch_configmap_data(self, name: str, namespace: str,
                             data: Dict[str, str]) -> Any:
        """Append keys to a ConfigMap in ONE merge-PATCH — no read-modify-
        write. PostBind's injection is a pure key append, so the GET half of
        mutate() is wasted work on the bind hot path (two round trips and
        two JSON codecs per pod, measured as the largest share of bind-task
        time under churn)."""
        doc = self._request(
            "PATCH", self._path("ConfigMap", namespace, name),
            {"data": dict(data)},
            content_type="application/merge-patch+json",
        )
        return _FROM_JSON["ConfigMap"](doc)

    def update(self, obj: Any, expect_rv: Optional[int] = None) -> Any:
        kind = obj.kind
        _, _, namespaced = _ROUTES[kind]
        ns = obj.metadata.namespace if namespaced else None
        if expect_rv is not None:
            # Compare-and-swap: PUT with metadata.resourceVersion — the
            # apiserver 409s on mismatch (leader election depends on this).
            body = obj_to_json(obj)
            body["metadata"]["resourceVersion"] = str(expect_rv)
            doc = self._request(
                "PUT", self._path(kind, ns, obj.metadata.name), body)
            return _FROM_JSON[kind](doc)
        doc = self._request(
            "PATCH", self._path(kind, ns, obj.metadata.name), obj_to_json(obj),
            content_type="application/merge-patch+json",
        )
        return _FROM_JSON[kind](doc)

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        self._request("DELETE", self._path(kind, namespace, name))

    def watch(self, kind: str, send_initial: bool = True) -> "KubeWatch":
        return KubeWatch(self, kind, send_initial)


class KubeWatch:
    """Streams watch events for one kind; same next()/stop() contract as
    cluster.apiserver.Watch (informers consume it unchanged).

    Reflector semantics on expiry: when the apiserver returns **410 Gone**
    (our resourceVersion was compacted away — routine after a disconnect) or
    an ERROR watch event, the stream cannot resume, so we re-LIST and emit a
    synthetic diff against the objects we have forwarded so far — ADDED for
    everything live (informers drop unchanged ones by resourceVersion) and
    DELETED for keys that vanished while we were blind. client-go's
    reflector does the same; the round-2 adapter retried the dead rv forever
    with a silently frozen cache (VERDICT.md missing #3)."""

    def __init__(self, server: KubeAPIServer, kind: str, send_initial: bool):
        self.server = server
        self.kind = kind
        self._q: "queue.Queue" = queue.Queue()
        self._stopped = threading.Event()
        self._known: Dict[str, Any] = {}  # key -> last object forwarded
        rv = "0"
        if send_initial:
            doc = server._request("GET", server._path(kind, None))
            rv = (doc.get("metadata") or {}).get("resourceVersion", "0")
            for item in doc.get("items", []):
                self._emit("ADDED", _FROM_JSON[kind](item))
        self._thread = threading.Thread(
            target=self._stream, args=(rv,), daemon=True,
            name=f"kubewatch-{kind}",
        )
        self._thread.start()

    def _emit(self, ev_type: str, obj: Any) -> None:
        key = obj.metadata.key
        if ev_type == "DELETED":
            self._known.pop(key, None)
        else:
            self._known[key] = obj
        self._q.put(WatchEvent(ev_type, obj))

    def _relist(self) -> str:
        """Fresh LIST; emit the synthetic diff. Returns the new list rv."""
        doc = self.server._request("GET", self.server._path(self.kind, None))
        live = {}
        for item in doc.get("items", []):
            obj = _FROM_JSON[self.kind](item)
            live[obj.metadata.key] = obj
        for key in list(self._known):
            if key not in live:
                self._emit("DELETED", self._known[key])
        for obj in live.values():
            self._emit("ADDED", obj)
        return (doc.get("metadata") or {}).get("resourceVersion", "0")

    def _stream(self, rv: str) -> None:
        while not self._stopped.is_set():
            try:
                path = self.server._path(self.kind, None) + (
                    f"?watch=1&allowWatchBookmarks=true&resourceVersion={rv}"
                )
                resp = self.server._request("GET", path, stream=True)
                for line in resp:
                    if self._stopped.is_set():
                        return
                    if not line.strip():
                        continue
                    ev = json.loads(line)
                    ev_type = ev.get("type", "")
                    obj_doc = ev.get("object") or {}
                    if ev_type == "ERROR":
                        # Status object; code 410 (or anything else fatal)
                        # means this stream is unresumable.
                        raise StatusError(
                            int(obj_doc.get("code", 410) or 410),
                            f"watch ERROR event: {obj_doc.get('message', '')}")
                    new_rv = (obj_doc.get("metadata") or {}).get(
                        "resourceVersion")
                    if new_rv:
                        rv = new_rv
                    if ev_type == "BOOKMARK":
                        continue
                    if ev_type not in ("ADDED", "MODIFIED", "DELETED"):
                        continue
                    self._emit(ev_type, _FROM_JSON[self.kind](obj_doc))
            except StatusError as e:
                if self._stopped.is_set():
                    return
                if e.code == 410:
                    log.warning("watch %s expired (410); re-listing",
                                self.kind)
                    try:
                        rv = self._relist()
                        continue
                    except Exception as le:  # noqa: BLE001 — retry below
                        log.warning("watch %s re-list failed (%s)",
                                    self.kind, le)
                else:
                    log.warning("watch %s dropped (%s); reconnecting",
                                self.kind, e)
                self._stopped.wait(1.0)
            except Exception as e:  # noqa: BLE001 — reconnect with backoff
                if self._stopped.is_set():
                    return
                log.warning("watch %s dropped (%s); reconnecting", self.kind, e)
                self._stopped.wait(1.0)

    _SENTINEL = object()

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if ev is KubeWatch._SENTINEL:
            return None  # informer run loops exit on None after stop()
        return ev

    def stop(self) -> None:
        self._stopped.set()
        self._q.put(KubeWatch._SENTINEL)
