"""High-level resource CRUD — parity with /root/reference/pkg/resources.

``Descriptor`` mirrors resources.Descriptor (pods.go:182-212): a convenience
wrapper the scheduler and agents use for pod/configmap/node operations.
Differences by design:
- ``append_to_pod_configmaps`` (parity: AppendToExistingConfigMapsInPod,
  pods.go:156-175) is atomic via APIServer.mutate — the reference does
  read-modify-Update with no conflict handling.
- ``get_node`` takes the node name (the reference's GetNode has the indexer
  key hardcoded to "k8s-aferik-master", nodes.go:28-37 — a bug SURVEY.md §2
  flags; we fix rather than reproduce it).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ..api.objects import ConfigMap, Node, Pod
from .apiserver import APIServer, NotFound


@dataclass
class PatchNodeParam:
    """Parity with resources.PatchNodeParam (nodes.go:14-26)."""

    node_name: str
    operator: str  # add | replace | remove
    path: str  # e.g. /metadata/labels/tpu.sched~1slice.config
    value: Dict[str, str]


class Descriptor:
    def __init__(self, server: APIServer) -> None:
        self.server = server

    # -- pods --------------------------------------------------------------
    def list_pods(self, namespace: Optional[str] = None, node_name: Optional[str] = None,
                  phase: Optional[str] = None) -> List[Pod]:
        def field_fn(p: Pod) -> bool:
            if node_name is not None and p.spec.node_name != node_name:
                return False
            if phase is not None and p.status.phase != phase:
                return False
            return True

        return self.server.list("Pod", namespace=namespace, field_fn=field_fn)

    def get_pod(self, name: str, namespace: str = "default") -> Pod:
        return self.server.get("Pod", name, namespace)

    def create_pod(self, pod: Pod) -> Pod:
        return self.server.create(pod)

    def bind_pod(self, name: str, namespace: str, node_name: str) -> Optional[Pod]:
        """The Bind verb: set spec.nodeName (upstream kube-scheduler does this
        through the binding subresource; the plugin never binds directly).
        Servers exposing a direct Binding POST (the REST adapter) take it —
        one round trip; host_ip is the kubelet's to report there. The
        in-memory path keeps filling host_ip so tests see a full object."""
        bind = getattr(self.server, "bind", None)
        if bind is not None:
            bind(name, namespace, node_name)
            return None
        host_ip = node_name
        try:
            node = self.get_node(node_name)
            if node.status.addresses:
                host_ip = node.status.addresses[0]
        except NotFound:
            pass

        def fn(p: Pod) -> None:
            p.spec.node_name = node_name
            p.status.host_ip = host_ip

        return self.server.mutate("Pod", name, namespace, fn)

    def set_pod_phase(self, name: str, namespace: str, phase: str) -> Pod:
        def fn(p: Pod) -> None:
            p.status.phase = phase

        return self.server.mutate("Pod", name, namespace, fn)

    def delete_pod(self, name: str, namespace: str = "default") -> None:
        """Parity with DeletePod grace-period-0 (pods.go:176-181) — used by
        the reference to bounce the profiler DaemonSet pod after MIG reshape
        (gpu_plugins.go:416-433)."""
        self.server.delete("Pod", name, namespace)

    def patch_pod(self, name: str, namespace: str, fn: Callable[[Pod], None]) -> Pod:
        return self.server.mutate("Pod", name, namespace, fn)

    # -- configmaps --------------------------------------------------------
    def create_configmap(self, cm: ConfigMap) -> ConfigMap:
        return self.server.create(cm)

    def get_configmap(self, name: str, namespace: str = "default") -> ConfigMap:
        return self.server.get("ConfigMap", name, namespace)

    def update_configmap(self, name: str, namespace: str, data: Dict[str, str]) -> ConfigMap:
        # Key-append is expressible as a single merge-PATCH; servers that
        # support it directly (the REST adapter) skip mutate's read half —
        # one round trip instead of two on the bind hot path.
        patch = getattr(self.server, "patch_configmap_data", None)
        if patch is not None:
            return patch(name, namespace, data)

        def fn(cm: ConfigMap) -> None:
            cm.data.update(data)

        return self.server.mutate("ConfigMap", name, namespace, fn)

    def append_to_pod_configmaps(self, pod: Pod, data: Dict[str, str]) -> List[str]:
        """Write ``data`` into every ConfigMap the pod EnvFrom-references —
        the device-assignment side channel (parity:
        AppendToExistingConfigMapsInPod pods.go:156-175; consumed by kubelet
        EnvFrom resolution, SURVEY.md §3.3). Returns names written."""
        written: List[str] = []
        for c in pod.spec.containers:
            for ref in c.env_from:
                try:
                    self.update_configmap(ref.name, pod.metadata.namespace, data)
                    written.append(ref.name)
                except NotFound:
                    continue
        return written

    # -- nodes -------------------------------------------------------------
    def list_nodes(self) -> List[Node]:
        return self.server.list("Node")

    def get_node(self, name: str) -> Node:
        return self.server.get("Node", name, "default")

    def label_node(self, param: PatchNodeParam) -> Node:
        """Parity with PatchNodeParam.LabelNode (nodes.go:39-67) — the
        mechanism the reference uses to trigger MIG repartitioning via the
        nvidia.com/mig.config label (gpu_plugins.go:402-410); ours carries
        tpu.sched/slice.config."""

        def fn(n: Node) -> None:
            if param.operator == "remove":
                for k in param.value:
                    n.metadata.labels.pop(k, None)
            else:
                n.metadata.labels.update(param.value)

        return self.server.mutate("Node", param.node_name, "default", fn)
