"""Shared informers, listers and indexers over the APIServer watch stream.

Mirrors the client-go machinery the reference bootstraps lazily inside Score
(gpu_plugins.go:785-796: NewSharedInformerFactory → configmap/pod listers →
configmap/node/pod indexers → Start + WaitForCacheSync) — but built once at
scheduler construction, not per-Score-call, and without package-level mutable
globals (the reference's latent race, SURVEY.md §5 "Race detection").
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, Optional

from .apiserver import APIServer, Watch, WatchEvent

log = logging.getLogger(__name__)


class Informer:
    def __init__(self, server: APIServer, kind: str) -> None:
        self._server = server
        self.kind = kind
        # RLock: add_event_handler replays synthetic ADDs while holding the
        # lock (ordering guarantee below), and a handler may legitimately
        # call back into list()/get().
        self._mu = threading.RLock()
        self._cache: Dict[str, Any] = {}
        self._synced = threading.Event()
        self._watch: Optional[Watch] = None
        self._thread: Optional[threading.Thread] = None
        self._started = False
        self._handlers: List[Dict[str, Callable[..., None]]] = []

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Start the watch loop. Informers are single-use (client-go
        semantics): once stopped they cannot be restarted — build a new
        factory instead."""
        if self._started:
            return
        self._started = True
        # Initial list under the same subscription guarantees no missed events.
        self._watch = self._server.watch(self.kind, send_initial=True)
        with self._mu:
            for obj in self._server.list(self.kind):
                self._cache[obj.metadata.key] = obj
            initial = list(self._cache.values())
            handlers = list(self._handlers)
            # _synced set inside the same critical section as the handler
            # snapshot: a handler registered concurrently either is in
            # ``handlers`` (registered before, no replay — it gets the loop
            # below) or sees _synced and replays the cache itself — never
            # neither, never both.
            self._synced.set()
        # Synthetic ADD delivery for the initial list — client-go semantics:
        # handlers registered before start() see every pre-existing object.
        # (The watch replay of these same objects is then dropped as stale by
        # _apply's resource_version check, so no double delivery.)
        for obj in initial:
            self._dispatch("ADDED", None, obj, handlers)
        self._thread = threading.Thread(
            target=self._run, name=f"informer-{self.kind}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._watch is not None:
            self._watch.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        assert self._watch is not None
        while True:
            ev = self._watch.next()
            if ev is None:
                return
            self._apply(ev)

    def _apply(self, ev: WatchEvent) -> None:
        key = ev.obj.metadata.key
        old = None
        with self._mu:
            old = self._cache.get(key)
            if ev.type == "DELETED":
                self._cache.pop(key, None)
            else:
                # Drop stale events (ABA on out-of-order delivery).
                if old is not None and old.metadata.resource_version >= ev.obj.metadata.resource_version:
                    return
                self._cache[key] = ev.obj
            # Snapshot handlers under the SAME lock as the cache update: a
            # handler registered after this point sees the object via its
            # synthetic-add replay instead, never both (exactly-once).
            handlers = list(self._handlers)
        self._dispatch(ev.type, old, ev.obj, handlers)

    def _dispatch(self, ev_type: str, old: Any, obj: Any, handlers: List[Dict[str, Callable[..., None]]]) -> None:
        # Handlers run outside the cache lock (so they may observe a cache
        # already newer than their event — same relaxation client-go makes).
        # A raising handler must not kill the watch thread.
        for h in handlers:
            try:
                if ev_type == "ADDED" and "on_add" in h:
                    h["on_add"](obj)
                elif ev_type == "MODIFIED" and "on_update" in h:
                    h["on_update"](old, obj)
                elif ev_type == "DELETED" and "on_delete" in h:
                    h["on_delete"](obj)
            except Exception:  # noqa: BLE001
                log.exception("informer %s handler failed on %s", self.kind, ev_type)

    def add_event_handler(
        self,
        on_add: Optional[Callable[[Any], None]] = None,
        on_update: Optional[Callable[[Any, Any], None]] = None,
        on_delete: Optional[Callable[[Any], None]] = None,
    ) -> None:
        """Register handlers. If the informer has already synced, ``on_add``
        is immediately invoked for every object in the cache (client-go's
        synthetic-add semantics for late handler registration)."""
        h: Dict[str, Callable[..., None]] = {}
        if on_add:
            h["on_add"] = on_add
        if on_update:
            h["on_update"] = on_update
        if on_delete:
            h["on_delete"] = on_delete
        # Append + replay in ONE critical section: _apply updates the cache
        # and snapshots handlers under the same lock, so an object arrives
        # either via the watch dispatch (handler already appended) or via
        # this replay (object already cached) — never both. Replaying while
        # still holding the lock also guarantees ordering: a concurrent
        # DELETE/MODIFY for a replayed object cannot reach this handler
        # before its synthetic ADD, because the watch thread's cache update
        # (which precedes its dispatch) blocks on the lock until the replay
        # finishes.
        with self._mu:
            self._handlers.append(h)
            if on_add and self._synced.is_set():
                for obj in list(self._cache.values()):
                    try:
                        on_add(obj)
                    except Exception:  # noqa: BLE001
                        log.exception("informer %s synthetic add failed", self.kind)

    def has_synced(self) -> bool:
        return self._synced.is_set()

    # -- lister / indexer --------------------------------------------------
    #
    # READ-ONLY CONTRACT: list()/get() return the cached objects by
    # reference, exactly as client-go listers do — callers MUST NOT mutate
    # them (mutate via Descriptor/APIServer instead, which deep-copies).
    # This keeps the hot scheduling path allocation-free.
    def list(
        self,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        filter_fn: Optional[Callable[[Any], bool]] = None,
    ) -> List[Any]:
        with self._mu:
            out = []
            for obj in self._cache.values():
                if namespace is not None and obj.metadata.namespace != namespace:
                    continue
                if label_selector and any(
                    obj.metadata.labels.get(k) != v for k, v in label_selector.items()
                ):
                    continue
                if filter_fn is not None and not filter_fn(obj):
                    continue
                out.append(obj)
            return out

    def get(self, name: str, namespace: str = "default") -> Optional[Any]:
        """Indexer GetByKey — parity with resources.Descriptor.Get
        (pkg/resources/pods.go:87-96); returns None on miss instead of the
        reference's hardcoded-key bug (nodes.go:28-37)."""
        with self._mu:
            return self._cache.get(f"{namespace}/{name}")


class SharedInformerFactory:
    def __init__(self, server: APIServer) -> None:
        self._server = server
        self._mu = threading.Lock()
        self._informers: Dict[str, Informer] = {}

    def informer(self, kind: str) -> Informer:
        with self._mu:
            inf = self._informers.get(kind)
            if inf is None:
                inf = Informer(self._server, kind)
                self._informers[kind] = inf
            return inf

    def start(self) -> None:
        with self._mu:
            informers = list(self._informers.values())
        for inf in informers:
            inf.start()

    def wait_for_cache_sync(self, timeout: float = 5.0) -> bool:
        with self._mu:
            informers = list(self._informers.values())
        return all(inf._synced.wait(timeout) for inf in informers)

    def stop(self) -> None:
        with self._mu:
            informers = list(self._informers.values())
        for inf in informers:
            inf.stop()
