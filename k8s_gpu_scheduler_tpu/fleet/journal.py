"""Router-side request journal — the durable truth a crash cannot lose.

An engine's in-flight state dies with it on a hard crash (no drain, no
snapshot — the cooperative PR 6 path never runs). What MUST survive is
not the KV cache (recomputable) but the router's record of what was
promised and what was already delivered: for every fleet request,
``(frid, prompt, max_new, trace_id, routed-to, deadline)`` written at
``submit()`` and the delivered-token stream appended after every
``step()``. With deterministic greedy decode, that record makes
recovery *token-identical*, not best-effort: failover re-submits
``prompt + delivered`` (minus a small verify window) to a surviving
replica, checks the regenerated window byte-equals the journal, and
streams only the undelivered suffix — the client sees one uninterrupted
stream, byte-equal to a no-fault run (fleet/router.py ``_failover``).

The journal is a pure-JSON/numpy structure — ``to_pytree`` packs one
JSON doc into a uint8 array exactly the way ``ServingSnapshot`` carries
its host bookkeeping — so ``utils/checkpoint.py``'s orbax machinery
persists it unchanged (``models/lifecycle.py persist_journal``) and a
restarted router re-opens it and replays every open entry. Closed
entries leave the map immediately (bounded size: the journal holds
in-flight state, not history) but their token counts stay in the
monotonic counters the ``tpu_fleet_*`` metrics and the chaos bench's
bounded-rework assertion read.

WIRE-FORMAT CONTRACT (graftcheck pass 11, ``wirecompat``): the
version-1 doc (top-level counters + per-entry ``JournalEntry`` fields)
is what a restarted router finds on disk — it must parse journals
written by the binary it replaced. The schema is pinned in
``tests/data/graftcheck/schemas/request_journal.json``. Evolve by
ADDING a ``JournalEntry`` field with a dataclass default (old docs
decode through ``JournalEntry(**d)`` untouched), then regenerate the
golden (``--update-schemas``) in the same change; removing or retyping
a field, or touching the required top-level counters, needs a doc
version bump with rationale. A PR 10-era doc is committed at
``tests/data/wire/journal_pr10.json`` and must keep loading
(tests/test_wire_compat.py).
"""
from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

import numpy as np

# Entry outcomes (close reasons).
DONE = "done"          # stream complete, delivered to the caller
ERROR = "error"        # surfaced failure (poison request, replay divergence)
EXPIRED = "expired"    # per-request deadline passed (submit(deadline_s=))
OUTCOMES = (DONE, ERROR, EXPIRED)


class JournalError(RuntimeError):
    """Journal misuse (unknown frid, double close, bad codec input)."""


@dataclass
class JournalEntry:
    """One fleet request's durable record. ``replica`` tracks where it
    currently computes (updated on shed and failover; None while
    orphaned — dead replica, no live target yet). ``delivered`` is the
    tokens the ROUTER has observed and streamed — the replay baseline;
    tokens an engine emitted but the router never read die with it, and
    replay regenerates them. ``deadline_wall`` is absolute wall clock
    (it must survive a router restart; monotonic clocks do not)."""

    frid: int
    prompt: List[int]
    max_new: int
    trace_id: Optional[str] = None
    replica: Optional[str] = None
    deadline_wall: Optional[float] = None
    submitted_wall: float = 0.0
    delivered: List[int] = field(default_factory=list)
    failovers: int = 0

    @property
    def remaining(self) -> int:
        return self.max_new - len(self.delivered)


class RequestJournal:
    """In-flight fleet requests + monotonic loss-accounting counters.
    Single-threaded like the router that owns it (the durable copy is
    the orbax persist, not a lock)."""

    def __init__(self) -> None:
        self._next_frid = 0
        self._open: Dict[int, JournalEntry] = {}
        # Monotonic counters (survive entry closure and the pytree
        # round trip): the metrics/bench contract reads these.
        self.delivered_tokens_total = 0
        self.closed = {o: 0 for o in OUTCOMES}

    # -- lifecycle ---------------------------------------------------------
    def open(self, prompt: List[int], max_new: int,
             trace_id: Optional[str] = None,
             replica: Optional[str] = None,
             deadline_wall: Optional[float] = None,
             submitted_wall: float = 0.0) -> int:
        """Record one submission; allocates and returns its fleet id
        (the journal owns the namespace so ids stay unique across a
        router restart)."""
        frid = self._next_frid
        self._next_frid += 1
        self._open[frid] = JournalEntry(
            frid=frid, prompt=[int(t) for t in prompt],
            max_new=int(max_new), trace_id=trace_id, replica=replica,
            deadline_wall=deadline_wall, submitted_wall=submitted_wall)
        return frid

    def entry(self, frid: int) -> JournalEntry:
        try:
            return self._open[frid]
        except KeyError:
            raise JournalError(f"unknown or closed fleet request {frid}") \
                from None

    def deliver(self, frid: int, tokens: List[int]) -> None:
        """Append newly delivered tokens (the router calls this after
        every step with each in-flight request's progress delta). The
        budget check runs BEFORE the mutation: an over-emitting engine
        (an accounting bug upstream) must not corrupt the entry — the
        journal is the recovery truth, and a negative ``remaining``
        would replay with an impossible budget."""
        if not tokens:
            return
        e = self.entry(frid)
        if len(e.delivered) + len(tokens) > e.max_new:
            raise JournalError(
                f"request {frid} would deliver "
                f"{len(e.delivered) + len(tokens)} tokens, "
                f"budget {e.max_new}")
        e.delivered.extend(int(t) for t in tokens)
        self.delivered_tokens_total += len(tokens)

    def reassign(self, frid: int, replica: Optional[str],
                 failover: bool = False) -> None:
        e = self.entry(frid)
        e.replica = replica
        if failover:
            e.failovers += 1

    def close(self, frid: int, outcome: str) -> JournalEntry:
        if outcome not in OUTCOMES:
            raise JournalError(f"unknown outcome {outcome!r}")
        e = self.entry(frid)
        del self._open[frid]
        self.closed[outcome] += 1
        return e

    # -- reads -------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._open)

    def __contains__(self, frid: int) -> bool:
        return frid in self._open

    def open_frids(self) -> List[int]:
        return sorted(self._open)

    def inflight_on(self, replica: Optional[str]) -> List[JournalEntry]:
        """Open entries currently computing on ``replica`` (None = the
        orphans awaiting a live target), in frid order — the replay set
        a death or a rejoin walks."""
        return [self._open[f] for f in sorted(self._open)
                if self._open[f].replica == replica]

    def stream(self, frid: int) -> List[int]:
        """The full delivered stream — what the caller receives; for a
        failed-over request this is pre-crash delivery + replayed
        suffix, byte-equal to the no-fault stream."""
        return list(self.entry(frid).delivered)

    # -- codec (pure JSON in a numpy carrier, the snapshot convention) -----
    def to_pytree(self) -> Dict[str, np.ndarray]:
        doc = {
            "version": 1,
            "next_frid": self._next_frid,
            "delivered_tokens_total": self.delivered_tokens_total,
            "closed": dict(self.closed),
            "entries": [asdict(self._open[f]) for f in sorted(self._open)],
        }
        raw = json.dumps(doc, sort_keys=True).encode()
        return {"journal_doc": np.frombuffer(raw, dtype=np.uint8).copy()}

    @staticmethod
    def from_pytree(tree: Dict[str, np.ndarray]) -> "RequestJournal":
        # The whole decode is guarded: a truncated orbax doc (partial
        # write at crash time — exactly the scenario this file exists
        # for) or a forward-versioned entry shape must surface as the
        # documented JournalError, not a raw JSONDecodeError/TypeError.
        try:
            raw = np.asarray(tree["journal_doc"], dtype=np.uint8)
            doc = json.loads(raw.tobytes().decode())
            if doc.get("version") != 1:
                raise JournalError(
                    f"unsupported journal version {doc.get('version')!r}")
            j = RequestJournal()
            j._next_frid = int(doc["next_frid"])
            j.delivered_tokens_total = int(doc["delivered_tokens_total"])
            j.closed.update({k: int(v) for k, v in doc["closed"].items()})
            for d in doc["entries"]:
                e = JournalEntry(**d)
                e.frid = int(e.frid)
                j._open[e.frid] = e
            return j
        except JournalError:
            raise
        except Exception as e:  # noqa: BLE001 — any malformed doc, one error type
            raise JournalError(f"not a journal pytree: {e}") from None
