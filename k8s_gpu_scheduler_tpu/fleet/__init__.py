"""Fleet serving tier: cache-aware routing + snapshot load shedding +
crash tolerance (replica health states, durable request journal,
deterministic-replay failover) + disaggregated prefill/decode pools
over N ``ContinuousBatcher`` replicas (see router.py / summary.py /
health.py / journal.py / pools.py)."""
from .health import (
    DEAD, HealthMonitor, HealthPolicy, LIVE, QUARANTINED, REJOINING,
    ReplicaHealth, STATES, SUSPECT,
)
from .journal import (
    DONE, ERROR, EXPIRED, JournalEntry, JournalError, RequestJournal,
)
from .pools import PoolPlan, PoolPolicy, plan_pools
from .router import FleetError, Router
from .summary import (
    MemoryStore, ReplicaSummary, list_summaries, prefix_match_len,
    prefix_match_parts, publish_summary, summarize,
)

__all__ = [
    "DEAD",
    "DONE",
    "ERROR",
    "EXPIRED",
    "FleetError",
    "HealthMonitor",
    "HealthPolicy",
    "JournalEntry",
    "JournalError",
    "LIVE",
    "MemoryStore",
    "PoolPlan",
    "PoolPolicy",
    "QUARANTINED",
    "REJOINING",
    "ReplicaHealth",
    "ReplicaSummary",
    "RequestJournal",
    "Router",
    "STATES",
    "SUSPECT",
    "list_summaries",
    "plan_pools",
    "prefix_match_len",
    "prefix_match_parts",
    "publish_summary",
    "summarize",
]
