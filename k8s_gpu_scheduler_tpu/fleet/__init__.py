"""Fleet serving tier: cache-aware routing + snapshot load shedding
over N ``ContinuousBatcher`` replicas (see router.py / summary.py)."""
from .router import FleetError, Router
from .summary import (
    MemoryStore, ReplicaSummary, list_summaries, prefix_match_len,
    publish_summary, summarize,
)

__all__ = [
    "FleetError",
    "MemoryStore",
    "ReplicaSummary",
    "Router",
    "list_summaries",
    "prefix_match_len",
    "publish_summary",
    "summarize",
]
