"""Replica health states — the crash-tolerance substrate of the fleet.

PR 6/8 made failure handling *cooperative*: preemption-safe serving
assumes the dying replica gets to run ``drain()``, and load shedding
assumes both ends are alive and willing. This module is the
non-cooperative half: a per-replica state machine

    live → suspect → dead → quarantined → rejoining → live

driven by three independent signals —

- **step exceptions**: ``Router.step()`` isolates each replica's raise,
  reports it here, and the consecutive-failure thresholds decide
  suspect (stop routing NEW requests to it) vs dead (failover its
  in-flight requests by journal replay). A hard ``ReplicaCrashed``
  (testing/faults.py) skips straight to dead: the engine object is
  gone, there is nothing to probe.
- **summary-heartbeat staleness**: a replica whose summary has not
  landed in the registry for ``stale_s`` is suspect, for the distinct
  (and longer) ``dead_s`` it is dead — the cross-process signal, since
  an out-of-process replica's only pulse is its published summary. The
  router guards this with a summary-PLANE liveness check: when no
  replica can publish (the store itself is down) staleness indicts the
  plane, not the replicas, and routing merely degrades (PR 8).
- **engine watchdog**: ``pool_metrics()``'s ``last_step_age_seconds``
  (0 when idle — PR 6) crossing ``watchdog_s`` with work pending means
  a wedged engine: steps are being attempted and not completing.

Dead replicas enter a **circuit-breaker quarantine**: the k-th death
costs a jittered-exponential hold (``utils/retry.py RetryPolicy`` — the
same bounded-backoff shape the control-plane clients use, jitter from a
seeded RNG so chaos runs stay replay-deterministic) and the policy's
``attempts`` bound turns a flapping replica into a permanently
quarantined one instead of letting it churn the fleet forever. After
the hold, the replica is ``rejoining``: the router rebuilds its engine
(``models/lifecycle.py resume_or_fresh`` — fresh after a crash, resumed
when a drained snapshot exists) and a successful probe returns it to
``live``; a failed rebuild re-quarantines with the next backoff rung.

The monitor is pure host-side bookkeeping driven by an injected clock
(virtual in tests), never touches an engine itself, and records every
transition — the router forwards them to the tracer
(``replica_dead``/``failover`` events) and to the
``tpu_fleet_replica_state{replica=,state=}`` gauge.
"""
from __future__ import annotations

import random
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, Optional, Tuple

from ..utils.retry import RetryPolicy

LIVE = "live"
SUSPECT = "suspect"
DEAD = "dead"
QUARANTINED = "quarantined"
REJOINING = "rejoining"
STATES = (LIVE, SUSPECT, DEAD, QUARANTINED, REJOINING)

# Default quarantine ladder: 0.2 s, 0.4 s, 0.8 s ... capped at 5 s,
# ±50% jitter, at most 8 rejoin attempts before the breaker latches
# open (the replica stays quarantined until an operator intervenes).
DEFAULT_QUARANTINE = RetryPolicy(attempts=8, base_s=0.2, multiplier=2.0,
                                 max_s=5.0, jitter=0.5)


@dataclass(frozen=True)
class HealthPolicy:
    """Thresholds for the state machine. ``dead_s`` must exceed
    ``stale_s``: staleness that merely degrades routing (PR 8's
    round-robin fallback) must trip long before staleness that declares
    a replica dead and replays its requests elsewhere — a replay races
    the original replica only if the two thresholds invert."""

    suspect_after: int = 1       # consecutive step errors → suspect
    dead_after: int = 3          # consecutive step errors → dead
    stale_s: float = 5.0         # heartbeat age → suspect
    dead_s: float = 15.0         # heartbeat age → dead (> stale_s)
    watchdog_s: float = 30.0     # engine last_step_age → dead (wedged)
    quarantine: RetryPolicy = field(default_factory=lambda: DEFAULT_QUARANTINE)

    def __post_init__(self) -> None:
        if self.dead_s <= self.stale_s:
            raise ValueError(
                f"dead_s ({self.dead_s}) must exceed stale_s "
                f"({self.stale_s}): a replica must degrade to stale "
                f"routing before it is declared dead")
        if self.dead_after < self.suspect_after:
            raise ValueError(
                f"dead_after ({self.dead_after}) must be >= "
                f"suspect_after ({self.suspect_after})")
        if self.suspect_after < 1:
            raise ValueError("suspect_after must be >= 1")


@dataclass
class ReplicaHealth:
    """One replica's mutable health record."""

    state: str = LIVE
    consecutive_errors: int = 0
    deaths: int = 0                      # quarantine backoff exponent
    quarantined_until: float = 0.0       # monotonic; inf = breaker open
    last_error: str = ""
    since: float = 0.0                   # monotonic time of last transition


class HealthMonitor:
    """Tracks N replicas' states; every mutation returns the transition
    it caused (``(old, new)`` or ``None``) so the caller can act —
    failover on ``* → dead``, re-enter rotation on ``rejoining → live``.
    Deterministic given the clock and the seed (jittered quarantine
    draws come from one seeded RNG consumed in event order)."""

    def __init__(self, policy: Optional[HealthPolicy] = None,
                 seed: int = 0) -> None:
        self.policy = policy or HealthPolicy()
        self._rng = random.Random(seed)
        self._replicas: Dict[str, ReplicaHealth] = {}
        self._transitions = 0
        # Transition log: (monotonic, replica, old, new, reason) — what
        # the chaos determinism gate compares (minus the clock column).
        # Bounded drop-oldest: a long-lived router's health history must
        # not be a slow leak; the counter above stays exact.
        self.events: Deque[Tuple[float, str, str, str, str]] = \
            deque(maxlen=512)

    # -- registration / reads ---------------------------------------------
    def add(self, replica_id: str, now: float = 0.0) -> None:
        self._replicas[replica_id] = ReplicaHealth(since=now)

    def get(self, replica_id: str) -> ReplicaHealth:
        return self._replicas[replica_id]

    def state(self, replica_id: str) -> str:
        return self._replicas[replica_id].state

    def routable(self, replica_id: str) -> bool:
        """May receive NEW requests (suspect replicas keep serving what
        they hold but stop accruing blast radius)."""
        return self._replicas[replica_id].state == LIVE

    def serving(self, replica_id: str) -> bool:
        """Should still be stepped (holds live work)."""
        return self._replicas[replica_id].state in (LIVE, SUSPECT)

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in STATES}
        for h in self._replicas.values():
            out[h.state] += 1
        return out

    @property
    def transition_count(self) -> int:
        return self._transitions

    # -- transitions -------------------------------------------------------
    def _move(self, rid: str, new: str, reason: str,
              now: float) -> Optional[Tuple[str, str]]:
        h = self._replicas[rid]
        old = h.state
        if old == new:
            return None
        h.state = new
        h.since = now
        self._transitions += 1
        self.events.append((now, rid, old, new, reason))
        return (old, new)

    def note_ok(self, rid: str, now: float) -> Optional[Tuple[str, str]]:
        """A successful step: clears the error run; a replica suspected
        FOR step errors redeems itself (live again). A heartbeat-stale
        suspect stays suspect — stepping fine says nothing about its
        summary reaching the store, and redeeming it here would flap
        suspect↔live every step while the staleness persists
        (``observe`` redeems it when the heartbeat is fresh again)."""
        h = self._replicas[rid]
        error_driven = h.consecutive_errors > 0
        h.consecutive_errors = 0
        if h.state == SUSPECT and error_driven:
            return self._move(rid, LIVE, "step ok", now)
        return None

    def note_error(self, rid: str, exc: BaseException,
                   now: float) -> Optional[Tuple[str, str]]:
        """A step exception (isolated by the router): escalate along the
        consecutive-failure ladder."""
        h = self._replicas[rid]
        h.consecutive_errors += 1
        h.last_error = f"{type(exc).__name__}: {exc}"
        if h.consecutive_errors >= self.policy.dead_after:
            return self._move(
                rid, DEAD,
                f"{h.consecutive_errors} consecutive step errors "
                f"({h.last_error})", now)
        if h.consecutive_errors >= self.policy.suspect_after:
            return self._move(rid, SUSPECT, h.last_error, now)
        return None

    def declare_dead(self, rid: str, reason: str,
                     now: float) -> Optional[Tuple[str, str]]:
        """Conclusive death (hard crash, watchdog, heartbeat dead_s):
        no ladder — the evidence is terminal."""
        h = self._replicas[rid]
        h.last_error = reason
        return self._move(rid, DEAD, reason, now)

    def observe(self, rid: str, now: float,
                heartbeat_age_s: Optional[float] = None,
                last_step_age_s: Optional[float] = None,
                ) -> Optional[Tuple[str, str]]:
        """Passive-signal check for a live/suspect replica: heartbeat
        staleness and the engine watchdog. Caller is responsible for the
        summary-plane liveness guard (don't indict replicas for a dead
        store)."""
        h = self._replicas[rid]
        if h.state not in (LIVE, SUSPECT):
            return None
        if last_step_age_s is not None \
                and last_step_age_s > self.policy.watchdog_s:
            return self._move(
                rid, DEAD,
                f"engine wedged: last step {last_step_age_s:.1f}s ago "
                f"(watchdog {self.policy.watchdog_s:.1f}s)", now)
        if heartbeat_age_s is not None:
            if heartbeat_age_s > self.policy.dead_s:
                return self._move(
                    rid, DEAD,
                    f"heartbeat {heartbeat_age_s:.1f}s stale "
                    f"(dead_s {self.policy.dead_s:.1f}s)", now)
            if heartbeat_age_s > self.policy.stale_s and h.state == LIVE:
                return self._move(
                    rid, SUSPECT,
                    f"heartbeat {heartbeat_age_s:.1f}s stale", now)
            if heartbeat_age_s <= self.policy.stale_s \
                    and h.state == SUSPECT \
                    and h.consecutive_errors == 0:
                # Heartbeat-driven suspicion lifts when the heartbeat is
                # fresh again (error-driven suspicion lifts in note_ok).
                return self._move(rid, LIVE, "heartbeat fresh", now)
        return None

    # -- circuit breaker ---------------------------------------------------
    def quarantine(self, rid: str, now: float) -> Optional[Tuple[str, str]]:
        """Dead → quarantined for the next jittered-backoff hold; past
        the policy's attempt bound the breaker latches open (hold =
        inf): a replica that keeps dying right after rejoining must stop
        costing the fleet failovers."""
        h = self._replicas[rid]
        h.deaths += 1
        h.consecutive_errors = 0
        if h.deaths >= self.policy.quarantine.attempts:
            h.quarantined_until = float("inf")
            return self._move(
                rid, QUARANTINED,
                f"breaker open after {h.deaths} deaths", now)
        hold = self.policy.quarantine.backoff_s(h.deaths, rng=self._rng)
        h.quarantined_until = now + hold
        return self._move(rid, QUARANTINED, f"hold {hold:.3f}s", now)

    def due_for_rejoin(self, rid: str, now: float) -> bool:
        h = self._replicas[rid]
        return h.state == QUARANTINED and now >= h.quarantined_until

    def start_rejoin(self, rid: str, now: float) -> Optional[Tuple[str, str]]:
        return self._move(rid, REJOINING, "quarantine expired", now)

    def rejoined(self, rid: str, now: float) -> Optional[Tuple[str, str]]:
        """Fresh engine built and probed: back in rotation. ``deaths``
        is deliberately NOT reset — a flapper's next quarantine is
        longer, which is the whole point of the breaker."""
        h = self._replicas[rid]
        h.consecutive_errors = 0
        return self._move(rid, LIVE, "rejoined", now)

    def rejoin_failed(self, rid: str, exc: BaseException,
                      now: float) -> Optional[Tuple[str, str]]:
        """Engine rebuild failed: back to quarantine on the next rung."""
        h = self._replicas[rid]
        h.last_error = f"{type(exc).__name__}: {exc}"
        self._move(rid, DEAD, f"rejoin failed: {h.last_error}", now)
        return self.quarantine(rid, now)
