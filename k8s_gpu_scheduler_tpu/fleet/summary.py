"""Replica state summaries — the routing substrate of the fleet tier.

A serving replica (one paged ``ContinuousBatcher``) periodically
publishes a compact summary of the two things a cache-aware router
needs: WHAT it has cached (a radix-tree digest — the top-K hottest
cached token-prefix paths, models/prefix_cache.py ``digest()``) and HOW
LOADED it is (free-page watermark, active slots, queue depth, recent
per-phase latency p50s drawn from the same ``pool_metrics()`` /
``tpu_serve_phase_duration_seconds`` plumbing the Prometheus exporter
consumes). The summary rides the registry under
``replica/<fleet>/<id>`` (registry/inventory.py key layout) exactly the
way node inventories do — the serving-tier analogue of the reference's
profiler writing GPU-UUID lists per node and the scheduler listing them
back (gpu_plugins.go:536-542): writer and reader share one typed schema
defined here once, and the lister uses the same chunked-MGET pattern
``list_inventories`` grew at fleet scale.

``prefix_match_len`` is the router's scoring primitive: an estimate of
how many prompt tokens a replica would serve from its cache, computed
AGAINST THE DIGEST — page-aligned and capped one page below full cover,
mirroring ``PrefixCache.match``'s contract (admission always leaves the
last page to prefill), so the score predicts exactly the prefill rows
admission will actually skip. Truncated digest paths under-claim, never
over-claim. Tiered replicas (KV tiering, PR 16) publish a third
per-path element — the RESIDENT token length — and
``prefix_match_parts`` splits a match into free-hit resident tokens vs
demoted tokens that pay a DRAM→HBM promotion upload, so the router can
price the upload without losing the hit.

``MemoryStore`` is the in-process registry stand-in (the
get/set/get_keys/mget subset of registry/client.py's ``Client``): a
single-process fleet — the bench, the tests, a dev loop — needs no
kvstored to route, while production passes the real RESP client and the
summaries ride the shared registry. Chaos tests wrap either in a
``FaultProxy`` to flap the summary plane and drive the router's
degraded path.

WIRE-FORMAT CONTRACT (graftcheck pass 11, ``wirecompat``): the
``to_json`` field set is the registry heartbeat schema every router in
the fleet parses — including routers a version behind the replica that
published it. It is pinned in
``tests/data/graftcheck/schemas/replica_summary.json``. Evolve by
ADDING a dataclass field with a default (the
``prefill_backlog_tokens``/``tp``/``weight_device_bytes``/
``dram_cached_pages`` precedents above — each one kept older summaries
parsing), then regenerate the golden (``--update-schemas``) in the
same change; only ``replica`` may stay default-less. A PR 8-era
heartbeat is committed at ``tests/data/wire/summary_pr8.json`` and
must keep loading (tests/test_wire_compat.py).
"""
from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..registry.inventory import REPLICA_KEY_PREFIX, replica_key


@dataclass
class ReplicaSummary:
    """One replica's published state: identity + seq/wall for staleness,
    pool watermarks + slot occupancy for load, per-phase p50s for the
    DistServe-style pressure split (decode p50 = TPOT pressure, prefill
    p50 = TTFT pressure), and the cache digest for prefix affinity."""

    replica: str
    fleet: str = "fleet"
    seq: int = 0
    published_wall: float = 0.0        # Clock.wall() — crosses processes
    page_size: int = 1
    pages_total: int = 0
    pages_free: int = 0
    n_slots: int = 0
    active_slots: int = 0
    queued: int = 0
    decode_p50_s: float = 0.0
    prefill_p50_s: float = 0.0
    # Admitted-but-unfinished prefill tokens (chunked prefill — the
    # engine's _prefill_backlog). Slots/pages alone cannot see a
    # long-prompt flood: a replica grinding through chunked prefills
    # looks as "free" as an idle one on those axes, so without this
    # field the router keeps landing new long prompts on it. Default 0
    # keeps pre-chunking summaries parsing.
    prefill_backlog_tokens: int = 0
    # Island width (multi-chip sharded serving, models/serving.py
    # mesh=): replicas of different tp coexist in one fleet — snapshots
    # are mesh-agnostic, so shed/failover crosses tp boundaries freely —
    # and operators read this to tell scale-UP replicas from scale-OUT
    # ones. Default 1 keeps pre-sharding summaries parsing.
    tp: int = 1
    # Per-chip model-weight residency (Megatron-sliced weights,
    # models/serving.py weight_sharding): 1/tp-sliced projections + the
    # replicated remainder — the capacity axis that distinguishes a
    # replica that actually FITS big weights per chip from a
    # replicated-weight one at the same tp. Default 0 keeps
    # pre-weight-sharding summaries parsing.
    weight_device_bytes: int = 0
    # Host-DRAM tier occupancy (KV tiering, models/serving.py
    # kv_tiering=): pages held off-pool that a match can promote back.
    # Capacity signal only — the per-path upload cost lives in the
    # digest tier flags below. Default 0 keeps pre-tiering summaries
    # parsing.
    dram_cached_pages: int = 0
    # Pool role (disaggregated serving, fleet/router.py pools=): which
    # phase this replica serves — "prefill" (admission + chunked
    # prefill, hands completed prefills off), "decode" (receives
    # handoffs), or "mixed" (colocated, today's engine). Default
    # "mixed" keeps pre-disagg summaries parsing.
    role: str = "mixed"
    # Lifetime speculative accept rate (proposals accepted / proposed,
    # models/serving.py spec gauges): how well this replica's current
    # traffic mix speculates — a router can prefer high-accept replicas
    # for throughput-priority requests. 0.0 on non-speculative replicas
    # and (default) on pre-speculation summaries.
    spec_accept_rate: float = 0.0
    # [(token path, full cached token length)], hottest first. Tiered
    # replicas publish 3-tuples (token path, cached length, RESIDENT
    # length): resident tokens hit for free, the demoted remainder
    # (cached - resident) pays a DRAM→HBM upload at admission. 2-tuples
    # (untiered replicas, pre-tiering summaries) mean fully resident.
    digest: List[Tuple[List[int], int]] = field(default_factory=list)

    def to_json(self) -> str:
        d = asdict(self)
        d["digest"] = [[list(map(int, e[0]))] + [int(x) for x in e[1:]]
                       for e in self.digest]
        return json.dumps(d, sort_keys=True)

    @staticmethod
    def from_json(raw: str) -> "ReplicaSummary":
        d = json.loads(raw)
        digest = [tuple([list(map(int, e[0]))] + [int(x) for x in e[1:]])
                  for e in d.pop("digest", [])]
        return ReplicaSummary(digest=digest, **d)

    @property
    def free_frac(self) -> float:
        return self.pages_free / self.pages_total if self.pages_total \
            else 0.0

    @property
    def free_slot_frac(self) -> float:
        return (1.0 - self.active_slots / self.n_slots) if self.n_slots \
            else 0.0


def summarize(engine, replica: str, fleet: str = "fleet", seq: int = 0,
              now_wall: float = 0.0, decode_p50_s: float = 0.0,
              prefill_p50_s: float = 0.0, top_k: int = 8,
              max_tokens: int = 512) -> ReplicaSummary:
    """Build a summary from a live engine's ``replica_stats()`` +
    ``cache_digest()`` (both cheap host reads — no device sync). The
    phase p50s come from the CALLER (the router keeps rolling windows
    over the ``pool_metrics()`` phase batches it already drains for the
    Prometheus export, so summarize never steals the batch)."""
    st = engine.replica_stats()
    return ReplicaSummary(
        replica=replica, fleet=fleet, seq=seq, published_wall=now_wall,
        page_size=int(st["page_size"]), pages_total=int(st["pages_total"]),
        pages_free=int(st["pages_free"]), n_slots=int(st["n_slots"]),
        active_slots=int(st["active_slots"]), queued=int(st["queued"]),
        decode_p50_s=float(decode_p50_s),
        prefill_p50_s=float(prefill_p50_s),
        prefill_backlog_tokens=int(st.get("prefill_backlog_tokens", 0)),
        tp=int(st.get("tp", 1)),
        weight_device_bytes=int(st.get("weight_device_bytes", 0)),
        dram_cached_pages=int(st.get("dram_cached_pages", 0)),
        role=str(st.get("role", "mixed")),
        spec_accept_rate=float(st.get("spec_accept_rate", 0.0)),
        digest=engine.cache_digest(top_k, max_tokens),
    )


def prefix_match_parts(prompt: Sequence[int],
                       digest: Sequence[Tuple[Sequence[int], int]],
                       page_size: int) -> Tuple[int, int]:
    """``(match tokens, resident tokens)`` a replica with this digest
    would serve for ``prompt``: the longest common token prefix against
    any digest path, floored to page granularity and capped so at least
    the prompt's last page prefills — the exact shape of
    ``PrefixCache.match``'s answer, predicted from the digest alone.
    ``resident`` ≤ ``match`` is the portion already in HBM; the
    remainder is demoted to the DRAM tier and pays a promotion upload
    at admission (2-tuple digest entries count as fully resident). Best
    entry by total match, resident length breaking ties — two replicas
    covering the same prefix differ only in upload cost."""
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    best, best_res = 0, 0
    for entry in digest:
        tokens, cached_len = entry[0], int(entry[1])
        res_len = int(entry[2]) if len(entry) > 2 else cached_len
        m = 0
        for a, b in zip(prompt, tokens):
            if int(a) != int(b):
                break
            m += 1
        cand = min(m, cached_len)
        cand_res = min(cand, res_len)
        if cand > best or (cand == best and cand_res > best_res):
            best, best_res = cand, cand_res
    match = _page_floor(best, len(prompt), page_size)
    resident = min(match, _page_floor(best_res, len(prompt), page_size))
    return match, resident


def _page_floor(tokens: int, prompt_len: int, page_size: int) -> int:
    pages = tokens // page_size
    if pages and pages * page_size == prompt_len:
        pages -= 1                   # the last page always re-prefills
    return pages * page_size


def prefix_match_len(prompt: Sequence[int],
                     digest: Sequence[Tuple[Sequence[int], int]],
                     page_size: int) -> int:
    """Total cached-prefix tokens (resident + demoted) — see
    ``prefix_match_parts`` for the tier split."""
    return prefix_match_parts(prompt, digest, page_size)[0]


def publish_summary(client, summary: ReplicaSummary) -> None:
    """Replica-side write (the profiler-client pattern, typed)."""
    client.set(replica_key(summary.fleet, summary.replica),
               summary.to_json())


def list_summaries(client, fleet: str) -> Dict[str, ReplicaSummary]:
    """Router-side listing: one chunked MGET per 512 replicas (the
    ``list_inventories`` pattern — kvstored's RESP reader caps a command
    at 1024 array elements). Unparseable values are skipped, not
    fatal — one corrupt writer must not blind the router to the rest of
    the fleet."""
    keys = client.get_keys(f"{REPLICA_KEY_PREFIX}{fleet}/*")
    if not keys:
        return {}
    mget = getattr(client, "mget", None)
    if callable(mget):
        values: List[Optional[str]] = []
        for i in range(0, len(keys), 512):
            values.extend(mget(*keys[i:i + 512]))
    else:
        values = [client.get(k) for k in keys]
    out: Dict[str, ReplicaSummary] = {}
    for raw in values:
        if raw is None:
            continue
        try:
            s = ReplicaSummary.from_json(raw)
        except (ValueError, TypeError, KeyError):
            continue
        if s.fleet == fleet:
            out[s.replica] = s
    return out


class MemoryStore:
    """Dict-backed stand-in for the registry ``Client`` subset the fleet
    uses (get/set/get_keys/mget/delete) — the in-process default so a
    single-binary fleet routes without a kvstored; swap in the real RESP
    client for a shared multi-process registry. No locking: the router
    drives it from one thread, and the real concurrent store is the
    registry server itself."""

    def __init__(self) -> None:
        self._kv: Dict[str, str] = {}

    def set(self, key: str, value: str) -> None:
        self._kv[key] = str(value)

    def get(self, key: str) -> Optional[str]:
        return self._kv.get(key)

    def mget(self, *keys: str) -> List[Optional[str]]:
        return [self._kv.get(k) for k in keys]

    def get_keys(self, pattern: str = "*") -> List[str]:
        if pattern.endswith("*"):
            pre = pattern[:-1]
            return sorted(k for k in self._kv if k.startswith(pre))
        return sorted(k for k in self._kv if k == pattern)

    def delete(self, *keys: str) -> int:
        return sum(1 for k in keys if self._kv.pop(k, None) is not None)
