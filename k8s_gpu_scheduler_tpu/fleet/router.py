"""Cache-aware fleet router — N serving replicas behind one admission
point, crash-tolerant.

One paged ``ContinuousBatcher`` is a replica, not a service; this module
is the fleet tier the ROADMAP's "millions of users" story needs. The
``Router`` fronts N in-process engine replicas and places each request
by SGLang-style cache-aware load balancing: every replica publishes a
:class:`~.summary.ReplicaSummary` (radix digest + pool watermarks +
per-phase p50s) into the registry, and admission scores

    score(replica) = (1 + resident_match
                          + DEMOTED_MATCH_DISCOUNT × demoted_match)
                     × (eps + free_page_frac)
                     × (eps + free_slot_frac)
                     × 1 / (1 + decode_p50 / p50_ref)
                     × 1 / (1 + prefill_backlog / backlog_ref)

taking the argmax with a deterministic tiebreak (lowest replica id —
same summaries, same placement, always). The match term routes shared
system prompts to the replica that already holds their KV (prefill cost
scales with the novel suffix — PR 4); demoted-match tokens (KV pages in
the host-DRAM tier, PR 16) count at ``DEMOTED_MATCH_DISCOUNT`` — the
pages skip prefill compute but pay a promotion upload, so they score
below resident, above a cold miss; the load terms keep a cold cache
from losing every request to a hot one; the latency term is the
DistServe observation that decode-phase pressure (TPOT) is the thing
co-placement hurts, so it is scored per-phase rather than folded into a
scalar load average. The backlog term is the prefill-phase complement
(chunked prefill, PR 9). When summaries are STALE routing degrades to
deterministic round-robin: worse placement, zero additional risk.

LOAD SHEDDING (cooperative, PR 8): ``shed()`` takes a partial
``ServingSnapshot`` off a hot replica (``drain(slots=...)``) and
``absorb()``s it into a cold one, token-identically, re-pointing the
router's fleet-level request ids through the returned rid mapping.

CRASH TOLERANCE (this layer's non-cooperative half) rests on three
pieces:

- a **health monitor** (fleet/health.py): per-replica
  ``live → suspect → dead → quarantined → rejoining`` driven by
  isolated step exceptions, summary-heartbeat staleness, and the
  engine's ``last_step_age`` watchdog, with a jittered-backoff
  quarantine (circuit breaker) and rejoin through
  ``models/lifecycle.py resume_or_fresh`` + an ``engine_factory``.
- a **request journal** (fleet/journal.py): ``submit()`` records
  ``(frid, prompt, max_new, trace_id, routed-to, deadline)`` and every
  ``step()`` appends each in-flight request's delivered-token progress
  (``ContinuousBatcher.emitted``); the journal is a pure-JSON/numpy
  pytree that persists through ``utils/checkpoint.py``
  (``checkpoint_journal()``) and survives a router restart.
- **failover by deterministic replay**: a replica declared dead has its
  engine object discarded (no drain — there is nobody to cooperate
  with) and every journaled in-flight request re-submitted to a
  surviving replica with ``prompt + delivered`` (minus a
  ``replay_verify_tokens`` window) as the new prompt. Greedy decode is
  deterministic, so the regenerated verify window must byte-equal the
  journal (divergence is surfaced, never silently streamed) and only
  the undelivered suffix streams to the caller: the end-to-end stream
  is byte-identical to a no-fault run. The radix prefix cache makes the
  replay prefill cheap where siblings share the prompt; chunked prefill
  bounds its interference. Rework is bounded: re-decoded (verify)
  tokens per failover ≤ journaled delivered tokens.

Per-request deadlines (``submit(deadline_s=)``) are enforced at the
router between steps: an expired request is cancelled on its engine
(pages retired — ``ContinuousBatcher.cancel``), surfaced in
``Router.errors`` (mirroring ``ContinuousBatcher.errors``), and its
journal entry closed — never silently stuck. ``run()`` is bounded by a
no-progress watchdog instead of spinning forever on a wedged fleet.

Threading: the router is a single-threaded driver (one step loop owns
all N engines — the same model the per-engine step loop already uses);
the concurrent surface is the registry, whose client is thread-safe and
retry-bounded on its own.
"""
from __future__ import annotations

from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..metrics.exporter import (
    FLEET_AFFINITY_HITS_TOTAL, FLEET_COUNTERS, FLEET_EXPIRED_TOTAL,
    FLEET_FAILOVERS_TOTAL, FLEET_GAUGES, FLEET_HANDOFF_DURATION,
    FLEET_HANDOFFS_TOTAL, FLEET_HISTOGRAMS, FLEET_JOURNAL_SIZE,
    FLEET_LOST_TOTAL, FLEET_MIGRATED_TOTAL, FLEET_REPLAYED_TOKENS_TOTAL,
    FLEET_REPLICA_ROLE, FLEET_REPLICA_STATE, FLEET_ROUTED_TOTAL,
    FLEET_SHED_TOTAL, export_decode_fallbacks, export_serving_pool,
)
from ..models.lifecycle import (
    load_journal, persist_journal, resume_or_fresh,
)
from ..models.snapshot import SnapshotError, check_fingerprint
from ..obs import SYSTEM_CLOCK
from ..testing.faults import InjectedFault, ReplicaCrashed
from .health import (
    DEAD, HealthMonitor, HealthPolicy, LIVE, QUARANTINED, REJOINING,
    STATES, SUSPECT,
)
from .journal import DONE, ERROR, EXPIRED, JournalError, RequestJournal
from .pools import PoolPlan, PoolPolicy, plan_pools
from .summary import (
    MemoryStore, ReplicaSummary, list_summaries, prefix_match_parts,
    publish_summary, summarize,
)

# Phases feeding the routing p50s (the names _obs_span records).
_DECODE_PHASES = ("decode_chunk", "verify")
_PREFILL_PHASES = ("prefill", "prefill_chunk")

# A demoted-path match is worth this fraction of a resident one: the
# pages exist (no prefill compute) but pay a DRAM→HBM promotion upload
# at admission. Strictly in (0, 1), so for the same digest path a
# resident replica always outscores a demoted one, and a demoted one
# always outscores a cold miss — the satellite ordering the KV-tiering
# issue pins.
DEMOTED_MATCH_DISCOUNT = 0.5


class FleetError(RuntimeError):
    """Fleet-level misuse or impossible operation (unknown replica,
    shed without capacity, heterogeneous fleet, no-progress watchdog)."""


def _p50(window) -> float:
    if not window:
        return 0.0
    xs = sorted(window)
    return xs[len(xs) // 2]


class _Replica:
    """Router-side state for one engine: identity, publish seq, the
    rolling phase-duration windows the summary p50s are computed from
    (fed by the same ``pool_metrics()`` phase batch the Prometheus
    export consumes — drained once, used twice), and the health inputs
    captured at publish time (heartbeat wall, watchdog age). ``engine``
    is None while the replica is dead/quarantined — a hard crash
    DISCARDS the object (no drain); rejoin installs a fresh one."""

    def __init__(self, replica_id: str, engine) -> None:
        self.id = replica_id
        self.engine = engine
        self.seq = 0
        self.decode_window: deque = deque(maxlen=256)
        self.prefill_window: deque = deque(maxlen=64)
        self.last_publish_wall = float("-inf")   # heartbeat (store ack'd)
        self.last_step_age = 0.0                 # watchdog (from publish)


class Router:
    """Admission front for N in-process paged engine replicas.

    ``replicas`` is a sequence of ``(id, ContinuousBatcher)`` pairs (ids
    unique; engines paged with one shared page_size — scoring compares
    page-aligned match lengths across them). ``store`` is the summary
    plane: any object with the registry client's get/set/get_keys
    (+mget) subset — defaults to an in-process :class:`MemoryStore`;
    pass the real registry ``Client`` to share summaries across
    processes. ``policy`` is ``"affinity"`` (cache-aware scoring, the
    point of this module) or ``"round_robin"`` (the baseline the bench
    leg beats). ``metrics`` is an optional metrics.exporter ``Registry``
    — when present every replica's ``pool_metrics()`` exports under a
    ``{replica=}`` label and the ``tpu_fleet_*`` counters/gauges are
    kept.

    Crash-tolerance knobs: ``health`` (a :class:`HealthPolicy`;
    thresholds + quarantine backoff), ``engine_factory`` (``rid -> new
    engine`` — without it a dead replica can never rejoin and stays
    quarantined), ``faults`` (a ``FaultInjector`` firing ``fleet.step``
    once per router step and ``replica.crash`` once per serving replica
    per step — kind="crash" hard-kills that replica), ``journal_dir``
    (orbax home for ``checkpoint_journal()``; when it already holds a
    journal the constructor recovers it and replays every open entry),
    ``replay_verify_tokens`` (re-decoded overlap per failover — the
    determinism check; 0 trusts the journal blindly),
    ``run_no_progress_s`` (the ``run()`` watchdog horizon)."""

    def __init__(self, replicas: Sequence[Tuple[str, object]],
                 store=None, fleet: str = "fleet",
                 pools: Optional[Dict[str, Sequence[str]]] = None,
                 policy: str = "affinity", stale_s: float = 5.0,
                 clock=None, tracer=None, metrics=None,
                 digest_top_k: int = 8, digest_max_tokens: int = 512,
                 p50_ref_s: float = 0.05, load_eps: float = 0.1,
                 backlog_ref_tokens: float = 2048.0,
                 auto_shed: bool = False,
                 shed_free_frac: float = 0.125,
                 shed_target_free_frac: float = 0.5,
                 health: Optional[HealthPolicy] = None,
                 health_seed: int = 0,
                 engine_factory: Optional[Callable[[str], object]] = None,
                 faults=None,
                 journal_dir: Optional[str] = None,
                 replay_verify_tokens: int = 4,
                 run_no_progress_s: float = 30.0) -> None:
        if not replicas:
            raise FleetError("a fleet needs at least one replica")
        if policy not in ("affinity", "round_robin"):
            raise FleetError(
                f"policy must be 'affinity' or 'round_robin', got "
                f"{policy!r}")
        if replay_verify_tokens < 0:
            raise FleetError(
                f"replay_verify_tokens must be >= 0, got "
                f"{replay_verify_tokens}")
        self._replicas: "OrderedDict[str, _Replica]" = OrderedDict()
        first_id: Optional[str] = None
        for rid, eng in replicas:
            rid = str(rid)
            if rid in self._replicas:
                raise FleetError(f"duplicate replica id {rid!r}")
            eng.replica_stats()          # paged-layout gate, fails early
            if first_id is None:
                first_id = rid
            else:
                # Fingerprint compatibility is validated HERE, not at
                # shed time: a partial drain removes the shed slots
                # from the source BEFORE absorb() runs its own
                # fingerprint check, so a mismatched pair discovered
                # mid-shed would strand the drained requests. With a
                # homogeneous fleet (everything but n_pages must
                # match — snapshot.check_fingerprint), absorb can only
                # refuse for capacity, which shed() prechecks. The same
                # reference vets every rejoining engine.
                try:
                    check_fingerprint(
                        self._replicas[first_id].engine.fingerprint(),
                        eng.fingerprint())
                except SnapshotError as e:
                    raise FleetError(
                        f"replica {rid!r} is not shed-compatible with "
                        f"{first_id!r}: {e}") from e
            self._replicas[rid] = _Replica(rid, eng)
        self._fingerprint_ref = \
            self._replicas[first_id].engine.fingerprint()
        self.page_size = int(
            self._replicas[first_id].engine.replica_stats()["page_size"])
        # Disaggregated pools (DistServe): ``pools`` PARTITIONS the
        # replica ids into a prefill pool (role='prefill' engines —
        # admission + chunked prefill, decode never dispatched) and a
        # decode pool (everything else). submit() then routes new
        # requests to the prefill pool only, and step() hands each
        # completed prefill off to the best decode replica via the
        # partial drain→absorb path. ``pools=None`` is the colocated
        # fallback — today's behavior, byte-identical — under which a
        # role='prefill' engine is REJECTED (its requests would park
        # at the phase boundary forever with nobody to hand off to).
        self._pools: Optional[Dict[str, List[str]]] = None
        self._pool_of: Dict[str, str] = {}
        if pools is not None:
            if set(pools) != {"prefill", "decode"}:
                raise FleetError(
                    f"pools needs exactly the keys 'prefill' and "
                    f"'decode', got {sorted(pools)}")
            pre = [str(r) for r in pools["prefill"]]
            dec = [str(r) for r in pools["decode"]]
            if not pre or not dec:
                raise FleetError(
                    "each pool needs at least one replica (a 1-replica "
                    "fleet runs colocated: pools=None)")
            both = pre + dec
            if (len(set(both)) != len(both)
                    or set(both) != set(self._replicas)):
                raise FleetError(
                    f"pools must partition the replica ids: pools name "
                    f"{sorted(both)}, fleet has "
                    f"{sorted(self._replicas)}")
            for r in pre:
                if getattr(self._replicas[r].engine, "role",
                           "mixed") != "prefill":
                    raise FleetError(
                        f"prefill-pool replica {r!r} must be built "
                        f"with role='prefill' (its engine would "
                        f"dispatch decode and race the handoff)")
            for r in dec:
                if getattr(self._replicas[r].engine, "role",
                           "mixed") == "prefill":
                    raise FleetError(
                        f"decode-pool replica {r!r} has role="
                        f"'prefill': it would never decode")
            self._pools = {"prefill": pre, "decode": dec}
            self._pool_of = {r: "prefill" for r in pre}
            self._pool_of.update({r: "decode" for r in dec})
        else:
            for rid, rep in self._replicas.items():
                if getattr(rep.engine, "role", "mixed") == "prefill":
                    raise FleetError(
                        f"replica {rid!r} has role='prefill' but the "
                        f"router has no pools= — its requests would "
                        f"never decode; pass pools= or build the "
                        f"engine role='mixed'")
        self.fleet = str(fleet)
        self.policy = policy
        self.stale_s = float(stale_s)
        self._store = store if store is not None else MemoryStore()
        self._clock = clock or SYSTEM_CLOCK
        self._tracer = tracer
        self._metrics = metrics
        self.digest_top_k = int(digest_top_k)
        self.digest_max_tokens = int(digest_max_tokens)
        self.p50_ref_s = float(p50_ref_s)
        self.load_eps = float(load_eps)
        self.backlog_ref_tokens = float(backlog_ref_tokens)
        self.auto_shed = bool(auto_shed)
        self.shed_free_frac = float(shed_free_frac)
        self.shed_target_free_frac = float(shed_target_free_frac)
        self.replay_verify_tokens = int(replay_verify_tokens)
        self.run_no_progress_s = float(run_no_progress_s)
        self._engine_factory = engine_factory
        self._faults = faults
        self._journal_dir = journal_dir
        if metrics is not None:
            self._c_routed = metrics.counter(
                FLEET_ROUTED_TOTAL, FLEET_COUNTERS[FLEET_ROUTED_TOTAL])
            self._c_shed = metrics.counter(
                FLEET_SHED_TOTAL, FLEET_COUNTERS[FLEET_SHED_TOTAL])
            self._c_migrated = metrics.counter(
                FLEET_MIGRATED_TOTAL, FLEET_COUNTERS[FLEET_MIGRATED_TOTAL])
            self._c_affinity = metrics.counter(
                FLEET_AFFINITY_HITS_TOTAL,
                FLEET_COUNTERS[FLEET_AFFINITY_HITS_TOTAL])
            self._c_failovers = metrics.counter(
                FLEET_FAILOVERS_TOTAL, FLEET_COUNTERS[FLEET_FAILOVERS_TOTAL])
            self._c_replayed = metrics.counter(
                FLEET_REPLAYED_TOKENS_TOTAL,
                FLEET_COUNTERS[FLEET_REPLAYED_TOKENS_TOTAL])
            self._c_lost = metrics.counter(
                FLEET_LOST_TOTAL, FLEET_COUNTERS[FLEET_LOST_TOTAL])
            self._c_expired = metrics.counter(
                FLEET_EXPIRED_TOTAL, FLEET_COUNTERS[FLEET_EXPIRED_TOTAL])
            # Counter registration is exposition-safe eager (a Counter
            # with no observations exports HELP/TYPE headers only); the
            # handoff-duration Histogram is NOT (it eagerly exposes a
            # zeroed unlabeled series), so it registers lazily at the
            # first handoff — a colocated fleet's exposition stays
            # byte-identical to pre-disagg output.
            self._c_handoffs = metrics.counter(
                FLEET_HANDOFFS_TOTAL, FLEET_COUNTERS[FLEET_HANDOFFS_TOTAL])
        # Health: every replica starts live; transitions drive failover.
        self._health = HealthMonitor(health, seed=health_seed)
        for rid in self._replicas:
            self._health.add(rid, now=self._clock.monotonic())
        # Fleet-level request ids: one namespace over all replicas —
        # local engine ids are replica-private and CHANGE on migration
        # (absorb assigns fresh ones), so callers hold fleet ids and the
        # router re-points the mapping at each shed/failover. The
        # JOURNAL owns the namespace (ids must stay unique across a
        # router restart).
        self._journal = RequestJournal()
        self._where: Dict[int, Tuple[str, int]] = {}   # frid -> (rid, lrid)
        self._local: Dict[Tuple[str, int], int] = {}   # (rid, lrid) -> frid
        # Engine tokens already consumed by the journal, per placement —
        # the progress cursor (a replayed placement restarts at 0 and
        # burns its verify window before delivering).
        self._consumed: Dict[Tuple[str, int], int] = {}
        # frid -> expected-but-not-yet-verified replay overlap.
        self._verify: Dict[int, List[int]] = {}
        self._req_metrics: Dict[int, Dict[str, float]] = {}
        # Surfaced request failures (deadline expiry, poison requests,
        # replay divergence) — the fleet mirror of
        # ``ContinuousBatcher.errors``: a request is never silently
        # stuck or silently dropped; it finishes or it lands here.
        self.errors: Dict[int, str] = {}
        self._rr = 0                                   # round-robin cursor
        self._handoff_rr = 0         # decode-target round-robin cursor
        self._handoffs = 0           # completed prefill→decode handoffs
        self._degraded = 0                             # degraded routes
        self._store_errors = 0
        self._failovers = 0
        self._replayed_tokens = 0
        self._lost = 0
        self._expired = 0
        # Parsed-summary cache, valid for one publish cycle: routing a
        # burst of submits between steps re-reads/re-parses nothing —
        # publish() (the only writer this router knows about)
        # invalidates it, so a shared-registry peer's update is picked
        # up at the next publish boundary at the latest.
        self._summaries_cache: Optional[Dict[str, ReplicaSummary]] = None
        if journal_dir:
            recovered = load_journal(journal_dir)
            if recovered is not None:
                # Router restart: every open entry's engine state died
                # with the old process — orphan them all and replay
                # (same machinery as a replica death).
                self._journal = recovered
                for frid in self._journal.open_frids():
                    self._journal.reassign(frid, None, failover=True)
        self.publish()                                 # summaries exist
        self._place_orphans()                          # recovered entries
        self._export_fleet_health()

    # -- summary plane -----------------------------------------------------
    def publish(self, replica_id: Optional[str] = None) -> None:
        """Publish summaries (one replica, or the whole fleet): drain
        each live engine's ``pool_metrics()`` once — feeding the rolling
        phase windows, the watchdog age, AND, when a metrics registry is
        attached, the ``{replica=}``-labeled Prometheus export — then
        write the summary to the store. A successful write is the
        replica's HEARTBEAT (health staleness reads the ack wall clock).
        Store failures are counted and swallowed: the registry client is
        retry-bounded, and an unreachable summary plane must degrade
        routing, never kill serving."""
        if self._metrics is not None:
            # Process-level (not per-replica): fused→dense downgrade
            # decisions, by reason — the never-silent gate of
            # serving._note_decode_fallback.
            from ..models.serving import decode_fallback_counts

            export_decode_fallbacks(self._metrics,
                                    decode_fallback_counts())
        reps = ([self._replica(replica_id)] if replica_id is not None
                else list(self._replicas.values()))
        for rep in reps:
            if rep.engine is None or not self._health.serving(rep.id):
                continue
            pm = rep.engine.pool_metrics()
            rep.last_step_age = float(
                pm.get("last_step_age_seconds", 0.0) or 0.0)
            for phase, seconds in pm.get("phase_durations") or ():
                if phase in _DECODE_PHASES:
                    rep.decode_window.append(float(seconds))
                elif phase in _PREFILL_PHASES:
                    rep.prefill_window.append(float(seconds))
            if self._metrics is not None:
                export_serving_pool(self._metrics, pm,
                                    labels={"replica": rep.id})
            rep.seq += 1
            s = summarize(
                rep.engine, rep.id, fleet=self.fleet, seq=rep.seq,
                now_wall=self._clock.wall(),
                decode_p50_s=_p50(rep.decode_window),
                prefill_p50_s=_p50(rep.prefill_window),
                top_k=self.digest_top_k,
                max_tokens=self.digest_max_tokens)
            try:
                publish_summary(self._store, s)
            except Exception:  # noqa: BLE001 — summary plane down ≠ serving down
                self._store_errors += 1
            else:
                rep.last_publish_wall = s.published_wall
        self._summaries_cache = None       # next route() re-reads once

    def summaries(self) -> Dict[str, ReplicaSummary]:
        """Summaries for THIS fleet's known replicas, from the store
        (an empty dict when the store is unreachable — the caller's
        staleness check then degrades routing). Cached per publish
        cycle: the store is read/parsed once per step, not once per
        submit."""
        if self._summaries_cache is not None:
            return dict(self._summaries_cache)
        try:
            listed = list_summaries(self._store, self.fleet)
        except Exception:  # noqa: BLE001 — summary plane down ≠ serving down
            self._store_errors += 1
            return {}
        out = {r: s for r, s in listed.items() if r in self._replicas}
        self._summaries_cache = out
        return dict(out)

    # -- scoring -----------------------------------------------------------
    def score(self, summary: ReplicaSummary,
              prompt: Sequence[int]) -> Tuple[float, int]:
        """(score, prefix match tokens) for placing ``prompt`` on the
        summarized replica — a pure function of its arguments, which is
        what makes placement deterministic and testable."""
        match, resident = prefix_match_parts(
            prompt, summary.digest, self.page_size)
        effective = resident + DEMOTED_MATCH_DISCOUNT * (match - resident)
        eps = self.load_eps
        load = ((eps + summary.free_frac)
                * (eps + summary.free_slot_frac)
                / (1.0 + summary.decode_p50_s / self.p50_ref_s)
                / (1.0 + max(0, summary.prefill_backlog_tokens)
                   / self.backlog_ref_tokens))
        return (1.0 + effective) * load, match

    def _routable_ids(self) -> List[str]:
        return [rid for rid in self._replicas
                if self._health.routable(rid)
                and self._replicas[rid].engine is not None]

    def route(self, prompt: Sequence[int]) -> Tuple[str, str, int]:
        """Choose a replica for ``prompt``: returns
        ``(replica id, policy used, prefix match tokens)``. Only LIVE
        replicas are candidates (suspect ones keep serving what they
        hold but take no new blast radius). Affinity scoring needs FRESH
        summaries (published within ``stale_s`` of now); with none
        fresh — or under ``policy="round_robin"`` — the deterministic
        round-robin fallback places the request instead (bounded
        staleness can degrade placement quality, never correctness)."""
        ids = self._routable_ids()
        if not ids:
            raise FleetError(
                f"no live replicas to route to "
                f"(states: {self._health.counts()})")
        if self._pools is not None:
            # Per-phase routing: NEW admissions go to the prefill pool
            # (chunked engines sized for TTFT), and reach the decode
            # pool only through the phase-boundary handoff. With the
            # whole prefill pool down, fall back to the decode pool —
            # its engines behave like mixed replicas (role='decode' is
            # advisory), so requests complete colocated-style instead
            # of stranding; requests_lost stays 0 either way.
            pool_ids = [r for r in ids
                        if self._pool_of[r] == "prefill"]
            ids = pool_ids or ids
        if self.policy == "affinity":
            now = self._clock.wall()
            fresh = {r: s for r, s in self.summaries().items()
                     if r in ids and now - s.published_wall <= self.stale_s}
            if fresh:
                best_rid, best_score, best_match = None, 0.0, 0
                for rid in sorted(fresh):
                    sc, match = self.score(fresh[rid], prompt)
                    if best_rid is None or sc > best_score:
                        best_rid, best_score, best_match = rid, sc, match
                return best_rid, "affinity", best_match
            self._degraded += 1
        rid = ids[self._rr % len(ids)]
        self._rr += 1
        return rid, ("round_robin" if self.policy == "round_robin"
                     else "degraded"), 0

    # -- serving API -------------------------------------------------------
    def submit(self, prompt, max_new: int,
               trace_id: Optional[str] = None,
               deadline_s: Optional[float] = None) -> int:
        """Route and admit one request; returns its FLEET id (stable
        across migrations and failovers — local engine ids are not).
        The submission is journaled BEFORE it reaches an engine: from
        here on a crash anywhere in the fleet can delay the stream but
        not lose it. ``deadline_s`` (relative seconds) arms per-request
        deadline enforcement: past it the request fails with a surfaced
        ``Router.errors`` record instead of sitting stuck."""
        prompt = [int(t) for t in prompt]
        if deadline_s is not None and deadline_s <= 0:
            raise FleetError(
                f"deadline_s must be positive, got {deadline_s}")
        rid, policy, match = self.route(prompt)
        now_wall = self._clock.wall()
        frid = self._journal.open(
            prompt, max_new, trace_id=trace_id, replica=rid,
            deadline_wall=(None if deadline_s is None
                           else now_wall + float(deadline_s)),
            submitted_wall=now_wall)
        eng = self._replica(rid).engine
        try:
            lrid = eng.submit(prompt, max_new=max_new, trace_id=trace_id)
        except Exception:
            # Admission refused (infeasible request) — the journal must
            # not carry an entry no engine holds, or the failover path
            # would replay a request that was never accepted.
            self._journal.close(frid, ERROR)
            raise
        self._where[frid] = (rid, lrid)
        self._local[(rid, lrid)] = frid
        self._consumed[(rid, lrid)] = 0
        if self._metrics is not None:
            self._c_routed.inc(replica=rid, policy=policy)
            if match:
                self._c_affinity.inc(replica=rid)
        if self._tracer is not None:
            self._tracer.event(
                "route", lane="router",
                rid=trace_id if trace_id is not None else f"fleet-{frid}",
                replica=rid, policy=policy, match_tokens=match)
        return frid

    def locate(self, frid: int) -> Tuple[str, int]:
        """(replica id, local request id) a fleet request currently
        lives on — moves when a shed or a failover migrates it."""
        if frid not in self._where:
            raise FleetError(f"unknown or finished fleet request {frid}")
        return self._where[frid]

    @property
    def pending(self) -> int:
        """In-flight work: live engines' queues/slots plus journaled
        orphans awaiting a live target (their dead replica's engine no
        longer counts them — the journal does)."""
        live = sum(r.engine.pending for r in self._replicas.values()
                   if r.engine is not None
                   and self._health.serving(r.id))
        return live + len(self._journal.inflight_on(None))

    # -- journal bookkeeping -----------------------------------------------
    def _ingest(self, frid: int, tokens,
                consumed: int) -> Optional[str]:
        """Feed one placement's engine-token progress into the journal:
        ``tokens[consumed:]`` first burns the replay verify window
        (byte-compare against the journaled delivery — greedy decode is
        deterministic, so a mismatch means the replay is NOT the same
        stream and must fail loudly), the rest is newly delivered.
        Returns a failure reason (caller fails THAT request — one bad
        stream must not unwind the fleet step) or None on success."""
        new = [int(t) for t in tokens[consumed:]]
        if not new:
            return None
        expect = self._verify.get(frid)
        if expect:
            k = min(len(new), len(expect))
            if new[:k] != expect[:k]:
                return ("replay divergence: regenerated tokens != "
                        "journaled delivery")
            del expect[:k]
            if not expect:
                self._verify.pop(frid, None)
            self._replayed_tokens += k
            if self._metrics is not None:
                self._c_replayed.inc(k)
            new = new[k:]
        if new:
            try:
                self._journal.deliver(frid, new)
            except JournalError as e:
                return f"journal refused delivery: {e}"
        return None

    def _drop_placement(self, frid: int) -> Optional[Tuple[str, int]]:
        loc = self._where.pop(frid, None)
        if loc is not None:
            self._local.pop(loc, None)
            self._consumed.pop(loc, None)
        self._verify.pop(frid, None)
        return loc

    def _fail_fleet_request(self, frid: int, reason: str,
                            outcome: str = ERROR,
                            cancel: bool = True) -> None:
        """Surface one fleet request's failure: engine-side cancel
        (pages retired), ``Router.errors`` record, journal entry
        closed."""
        loc = self._drop_placement(frid)
        if cancel and loc is not None:
            rep = self._replicas.get(loc[0])
            if rep is not None and rep.engine is not None:
                try:
                    rep.engine.cancel(loc[1], reason=reason)
                except Exception:  # noqa: BLE001 — engine may be dying too
                    pass
        self.errors[frid] = reason
        if frid in self._journal:
            self._journal.close(frid, outcome)

    def _collect_engine_errors(self, rep: _Replica) -> None:
        """Mirror per-request engine failures (poison isolation,
        ``ContinuousBatcher.errors``) into fleet errors + journal
        closure — the request already lost its slot and pages."""
        errs = rep.engine.errors
        if not errs:
            return
        for (rid_, lrid), frid in list(self._local.items()):
            if rid_ == rep.id and lrid in errs:
                reason = errs[lrid]
                self._drop_placement(frid)
                self.errors[frid] = reason
                if frid in self._journal:
                    self._journal.close(frid, ERROR)

    # -- health / failover -------------------------------------------------
    def _note_transition(self, rid: str,
                         transition: Optional[Tuple[str, str]],
                         reason: str = "") -> None:
        if transition is None:
            return
        old, new = transition
        if self._tracer is not None:
            name = "replica_dead" if new == DEAD else f"replica_{new}"
            self._tracer.event(name, lane="router", replica=rid,
                               prev=old, reason=reason)

    def _crash(self, rid: str, exc: BaseException) -> None:
        """Hard kill: the engine object is discarded — no drain, no
        snapshot, exactly what an OOM/wedged-device/killed-pod leaves
        behind. Recovery is journal replay only."""
        rep = self._replica(rid)
        rep.engine = None
        now = self._clock.monotonic()
        tr = self._health.declare_dead(rid, f"crash: {exc}", now)
        self._note_transition(rid, tr, f"crash: {exc}")
        self._on_dead(rid)

    def _on_dead(self, rid: str) -> None:
        """A replica was declared dead: discard its engine, orphan its
        journaled in-flight requests (replayed onto survivors by
        ``_place_orphans``), quarantine it (circuit breaker), and
        account the failover. Requests a dead replica held WITHOUT a
        journal entry would be lost — that counter must stay 0 (every
        router submission is journaled at admission)."""
        now = self._clock.monotonic()
        rep = self._replica(rid)
        rep.engine = None                  # dead = discarded, uniformly
        orphaned = 0
        for (rid_, _lrid), frid in list(self._local.items()):
            if rid_ != rid:
                continue
            self._drop_placement(frid)
            if frid in self._journal:
                self._journal.reassign(frid, None, failover=True)
                orphaned += 1
            else:
                self._lost += 1
                if self._metrics is not None:
                    self._c_lost.inc(replica=rid)
        self._failovers += 1
        if self._metrics is not None:
            self._c_failovers.inc(replica=rid)
        if self._tracer is not None:
            self._tracer.event("failover", lane="router", replica=rid,
                               orphaned=orphaned,
                               reason=self._health.get(rid).last_error)
        tr = self._health.quarantine(rid, now)
        self._note_transition(rid, tr)
        self._place_orphans()

    def _replay_entry(self, entry) -> bool:
        """Re-place one journaled in-flight request by deterministic
        replay: the new prompt is ``prompt + delivered`` minus the
        verify window, the new budget is the undelivered remainder plus
        that window. The replayed stream's first ``overlap`` tokens must
        byte-equal the journal (checked incrementally in ``_ingest``);
        only tokens past the window are delivered — the caller's stream
        is byte-identical to a no-fault run, and the re-decoded rework
        is bounded by the journaled delivery."""
        overlap = min(self.replay_verify_tokens, len(entry.delivered))
        ctx = list(entry.prompt) + list(entry.delivered)
        replay_prompt = ctx[:len(ctx) - overlap] if overlap else ctx
        budget = entry.remaining + overlap
        # Prefer the scored route (prefix affinity makes the replay
        # prefill cheap where siblings share the prompt), then fall
        # back to every other live replica — capacity refusals must not
        # strand a request that some replica could hold.
        try:
            first, _policy, _match = self.route(replay_prompt)
        except FleetError:
            return False                   # no live replicas right now
        candidates = [first] + [r for r in self._routable_ids()
                                if r != first]
        for rid in candidates:
            eng = self._replicas[rid].engine
            try:
                lrid = eng.submit(replay_prompt, max_new=budget,
                                  trace_id=entry.trace_id)
            except (ValueError, RuntimeError):
                continue                   # can't fit here; try the next
            frid = entry.frid
            self._where[frid] = (rid, lrid)
            self._local[(rid, lrid)] = frid
            self._consumed[(rid, lrid)] = 0
            if overlap:
                self._verify[frid] = list(entry.delivered[-overlap:])
            self._journal.reassign(frid, rid)
            if self._tracer is not None:
                self._tracer.event(
                    "replay", lane="router",
                    rid=(entry.trace_id if entry.trace_id is not None
                         else f"fleet-{frid}"),
                    replica=rid, resumed_at=len(entry.delivered),
                    verify_tokens=overlap)
            flight = getattr(eng, "_flight", None)
            if flight is not None:
                flight.record("replay", frid=frid, lrid=lrid,
                              resumed_at=len(entry.delivered),
                              verify_tokens=overlap)
            return True
        return False

    def _place_orphans(self) -> int:
        """Replay every journaled request with no live placement (dead
        replica, router restart). Unplaceable entries stay orphaned and
        are retried each step — ``run()``'s no-progress watchdog bounds
        the wait."""
        placed = 0
        for entry in self._journal.inflight_on(None):
            if self._replay_entry(entry):
                placed += 1
        return placed

    def _tick_health(self) -> None:
        """Passive health pass, once per step: quarantine expiry →
        rejoin (fresh engine via ``engine_factory`` +
        ``resume_or_fresh``, fingerprint-vetted, failure re-quarantines
        on the next backoff rung), then heartbeat-staleness and watchdog
        checks over the publish-time captures. Staleness only indicts a
        replica when the summary PLANE is alive (some other replica
        published fresh): a dead store degrades routing (PR 8), it does
        not kill the fleet."""
        now = self._clock.monotonic()
        now_wall = self._clock.wall()
        for rep in self._replicas.values():
            st = self._health.state(rep.id)
            if st == QUARANTINED \
                    and self._health.due_for_rejoin(rep.id, now) \
                    and self._engine_factory is not None:
                tr = self._health.start_rejoin(rep.id, now)
                self._note_transition(rep.id, tr)
                try:
                    eng, _resumed = resume_or_fresh(
                        lambda: self._engine_factory(rep.id),
                        self._rejoin_dir(rep.id))
                    eng.replica_stats()          # paged + alive probe
                    check_fingerprint(self._fingerprint_ref,
                                      eng.fingerprint())
                except Exception as e:  # noqa: BLE001 — rejoin must not kill the fleet
                    rep.engine = None
                    tr = self._health.rejoin_failed(rep.id, e, now)
                    self._note_transition(rep.id, tr, str(e))
                    continue
                rep.engine = eng
                rep.last_step_age = 0.0
                # Fresh heartbeat baseline BEFORE the publish attempt: a
                # replica that died by staleness still carries its
                # pre-death publish wall, and one dropped store write at
                # rejoin time must not let the next observe() pass
                # re-declare the healthy rebuild dead in the same tick.
                rep.last_publish_wall = now_wall
                tr = self._health.rejoined(rep.id, now)
                self._note_transition(rep.id, tr)
                self.publish(rep.id)             # heartbeat + summary
                self._place_orphans()            # capacity came back
        serving = [rep for rep in self._replicas.values()
                   if self._health.serving(rep.id)]
        ages = {rep.id: now_wall - rep.last_publish_wall
                for rep in serving}
        plane_ok = any(a <= self._health.policy.stale_s
                       for a in ages.values())
        for rep in serving:
            tr = self._health.observe(
                rep.id, now,
                heartbeat_age_s=(ages[rep.id] if plane_ok else None),
                last_step_age_s=rep.last_step_age)
            self._note_transition(rep.id, tr)
            if self._health.state(rep.id) == DEAD:
                self._on_dead(rep.id)

    def _rejoin_dir(self, rid: str) -> Optional[str]:
        """Snapshot directory a rejoining replica may resume from —
        None in-process (a hard crash never drained; resume_or_fresh
        then builds fresh). A cross-process deployment points this at
        the replica's pod volume."""
        return None

    def _enforce_deadlines(self) -> None:
        now_wall = self._clock.wall()
        for frid in self._journal.open_frids():
            e = self._journal.entry(frid)
            if e.deadline_wall is None or now_wall < e.deadline_wall:
                continue
            self._expired += 1
            if self._metrics is not None:
                self._c_expired.inc()
            if self._tracer is not None:
                self._tracer.event(
                    "deadline_expired", lane="router",
                    rid=(e.trace_id if e.trace_id is not None
                         else f"fleet-{frid}"),
                    delivered=len(e.delivered), budget=e.max_new)
            self._fail_fleet_request(
                frid,
                f"deadline exceeded after "
                f"{now_wall - e.submitted_wall:.3f}s "
                f"({len(e.delivered)}/{e.max_new} tokens delivered)",
                outcome=EXPIRED)

    def _export_fleet_health(self) -> None:
        if self._metrics is None:
            return
        g_state = self._metrics.gauge(FLEET_REPLICA_STATE,
                                      FLEET_GAUGES[FLEET_REPLICA_STATE])
        for rid in self._replicas:
            st = self._health.state(rid)
            for s in STATES:
                g_state.set(1.0 if s == st else 0.0,
                            replica=rid, state=s)
        # Pool topology, one-hot like replica_state: pools= mode labels
        # by pool membership (the router's routing truth even for a
        # mixed-role engine placed in the decode pool); colocated
        # fleets label every replica "mixed".
        g_role = self._metrics.gauge(FLEET_REPLICA_ROLE,
                                     FLEET_GAUGES[FLEET_REPLICA_ROLE])
        for rid in self._replicas:
            role = (self._pool_of[rid] if self._pools is not None
                    else "mixed")
            for r in ("mixed", "prefill", "decode"):
                g_role.set(1.0 if r == role else 0.0,
                           replica=rid, role=r)
        self._metrics.gauge(
            FLEET_JOURNAL_SIZE,
            FLEET_GAUGES[FLEET_JOURNAL_SIZE]).set(float(len(self._journal)))

    # -- stepping ----------------------------------------------------------
    def step(self) -> Dict[int, list]:
        """Step every serving replica once (admission + one
        decode/verify chunk each) WITH per-replica fault isolation: one
        replica's raise marks it suspect/dead and the step continues —
        the bugfix for the old all-or-nothing unwind — then journal the
        progress, enforce deadlines, replay orphans, refresh the
        published summaries, and return the newly finished streams keyed
        by FLEET id (each the full journaled delivery — for a
        failed-over request that is pre-crash tokens + replayed suffix,
        byte-identical to the no-fault stream). With ``auto_shed`` on, a
        replica past the pressure watermark sheds toward the coldest
        peer after the step."""
        done: Dict[int, list] = {}
        if self._faults is not None:
            try:
                self._faults.fire("fleet.step")
            except ReplicaCrashed:
                raise                      # a router crash is the driver's
            except InjectedFault:
                return done                # router step dropped: no work
        self._tick_health()
        now = self._clock.monotonic()
        for rep in list(self._replicas.values()):
            if rep.engine is None or not self._health.serving(rep.id):
                continue
            if self._faults is not None:
                try:
                    self._faults.fire("replica.crash",
                                      drop_exc=ReplicaCrashed)
                except InjectedFault as e:
                    self._crash(rep.id, e)
                    continue
            if not rep.engine.pending:
                # An idle engine cannot be wedged; a suspect one
                # redeems itself by having nothing to fail at.
                self._health.note_ok(rep.id, now)
                continue
            try:
                finished = rep.engine.step()
            except Exception as e:  # noqa: BLE001 — per-replica isolation (the point)
                tr = self._health.note_error(rep.id, e, now)
                self._note_transition(rep.id, tr, str(e))
                if self._health.state(rep.id) == DEAD:
                    self._on_dead(rep.id)
                continue
            self._health.note_ok(rep.id, now)
            metrics = rep.engine.pop_request_metrics()
            self._collect_engine_errors(rep)
            for lrid, toks in finished.items():
                frid = self._local.pop((rep.id, lrid), None)
                if frid is None:
                    continue                 # not router-owned (warmup)
                self._where.pop(frid, None)
                consumed = self._consumed.pop((rep.id, lrid), 0)
                reason = self._ingest(frid, toks, consumed)
                if reason is not None:
                    self._fail_fleet_request(frid, reason, cancel=False)
                    continue
                if self._verify.get(frid):
                    # Finished with verify window left unregenerated: a
                    # correct replay's budget (remaining + window) always
                    # regenerates the full window plus at least one new
                    # token, so stopping short IS divergence (e.g. an
                    # eos the journaled stream never contained) — fail
                    # loudly, never close DONE with a truncated stream.
                    self._fail_fleet_request(
                        frid, "replay divergence: replayed stream ended "
                        "inside the verify window", cancel=False)
                    continue
                done[frid] = self._journal.stream(frid)
                self._journal.close(frid, DONE)
                if lrid in metrics:
                    self._req_metrics[frid] = metrics[lrid]
            for (rid_, lrid), frid in list(self._local.items()):
                if rid_ != rep.id:
                    continue
                toks = rep.engine.emitted(lrid)
                consumed = self._consumed.get((rid_, lrid), 0)
                if len(toks) <= consumed:
                    continue
                reason = self._ingest(frid, toks, consumed)
                if reason is not None:
                    self._fail_fleet_request(frid, reason)
                    continue
                self._consumed[(rid_, lrid)] = len(toks)
        if self._pools is not None:
            # Phase boundary: every prefill-pool slot whose prompt is
            # fully resident (first token emitted, journaled by the
            # progress pass above) hands off to the decode pool now.
            self._auto_handoff()
        self._enforce_deadlines()
        self._place_orphans()
        self.publish()
        self._export_fleet_health()
        if self.auto_shed:
            self.maybe_shed()
        return done

    def _progress_marker(self) -> Tuple:
        # Deliberately NOT health.transition_count: a replica flapping
        # suspect↔live would register as perpetual "progress" and defeat
        # the watchdog. Recovery that matters shows up here anyway — a
        # rejoin that re-places orphans moves journal/pending.
        return (self._journal.delivered_tokens_total,
                sum(self._journal.closed.values()),
                len(self._journal), self.pending)

    def run(self, no_progress_s: Optional[float] = None) -> Dict[int, list]:
        """Drain everything submitted across the fleet, bounded by a
        no-progress watchdog: ``while pending`` alone would spin forever
        on a wedged or permanently-quarantined fleet — if no token is
        delivered, no request closes, and the journaled/pending work
        doesn't move for ``no_progress_s`` (monotonic), raise instead.
        A rejoin that matters re-places orphans (journal/pending move),
        so a recovering fleet is never killed mid-backoff as long as
        the horizon exceeds the quarantine ladder."""
        horizon = (self.run_no_progress_s if no_progress_s is None
                   else float(no_progress_s))
        done: Dict[int, list] = {}
        last_progress = self._clock.monotonic()
        marker = self._progress_marker()
        while self.pending:
            done.update(self.step())
            now = self._clock.monotonic()
            m = self._progress_marker()
            if m != marker:
                marker, last_progress = m, now
            elif now - last_progress >= horizon:
                raise FleetError(
                    f"fleet made no progress for {now - last_progress:.1f}s: "
                    f"{self.pending} pending, "
                    f"{len(self._journal)} journaled in flight, "
                    f"states {self._health.counts()}")
        return done

    def pop_request_metrics(self) -> Dict[int, Dict[str, float]]:
        """Per-request latency records (ttft_s/latency_s/tokens) keyed
        by fleet id, drained since the last call — migration-safe: a
        shed request's record closes on the replica that finished it,
        with the handoff gap charged (absorb rebases the clocks)."""
        out, self._req_metrics = self._req_metrics, {}
        return out

    # -- durability --------------------------------------------------------
    @property
    def journal(self) -> RequestJournal:
        return self._journal

    def checkpoint_journal(self) -> None:
        """Persist the journal under ``journal_dir`` via orbax
        (models/lifecycle.py): a restarted router recovers it and
        replays every open entry — the request-level analogue of the
        serve loop's snapshot persistence, for the crash that never
        drained."""
        if not self._journal_dir:
            raise FleetError("router built without journal_dir")
        persist_journal(self._journal, self._journal_dir)

    @property
    def health(self) -> HealthMonitor:
        return self._health

    # -- load shedding -----------------------------------------------------
    def _replica(self, rid: str) -> _Replica:
        try:
            return self._replicas[str(rid)]
        except KeyError:
            raise FleetError(f"unknown replica {rid!r}") from None

    def _repoint(self, src: str, dst: str,
                 mapping: Dict[int, int]) -> int:
        """Re-point the fleet-id bookkeeping after an absorb moved
        requests ``src → dst`` with the returned ``{old local rid: new
        local rid}`` mapping — shared by shed() and the disagg handoff.
        The delivered-progress cursor rides along: absorb carries the
        emitted stream, so the target's ``emitted()`` continues at the
        same offset."""
        src, dst = str(src), str(dst)
        moved = 0
        for (rid, lrid), frid in list(self._local.items()):
            if rid == src and lrid in mapping:
                del self._local[(rid, lrid)]
                new_key = (dst, mapping[lrid])
                self._local[new_key] = frid
                self._where[frid] = new_key
                self._consumed[new_key] = self._consumed.pop(
                    (rid, lrid), 0)
                if frid in self._journal:
                    # reassign() only moves the placement: trace_id,
                    # submitted_wall and — critically — deadline_wall
                    # are untouched, so a handed-off request keeps its
                    # ORIGINAL deadline (and a decode-pool crash later
                    # replays with it too).
                    self._journal.reassign(frid, dst)
                moved += 1
        return moved

    # -- disaggregated handoff ---------------------------------------------
    def _pick_decode_target(self, need_pages: int,
                            ctx: Sequence[int]) -> Optional[str]:
        """Best decode-pool replica for one completed prefill: hard
        capacity precheck on LIVE stats (≥1 free slot and room for the
        migrating pages — an absorb refusal after the drain would
        orphan the request), then conversation affinity + free-capacity
        scoring over fresh summaries (``ctx`` is prompt + delivered, so
        a multi-turn conversation lands where its earlier turns left
        cached pages). With any candidate's summary stale, degrade to a
        deterministic round-robin cursor over the candidates — same
        bounded-staleness posture as route()."""
        pool = self._pools["decode"]
        cands = []
        for rid in pool:
            rep = self._replicas[rid]
            if rep.engine is None or not self._health.routable(rid):
                continue
            st = rep.engine.replica_stats()
            if (st["n_slots"] - st["active_slots"] < 1
                    or st["pages_free"] < need_pages):
                continue
            cands.append(rid)
        if not cands:
            return None
        now = self._clock.wall()
        fresh = {r: s for r, s in self.summaries().items()
                 if now - s.published_wall <= self.stale_s}
        if all(r in fresh for r in cands):
            scored = sorted(
                ((self.score(fresh[r], ctx)[0], r) for r in cands),
                key=lambda t: (-t[0], t[1]))
            return scored[0][1]
        rid = cands[self._handoff_rr % len(cands)]
        self._handoff_rr += 1
        return rid

    def _handoff_slot(self, src: str, slot: int, lrid: int,
                      frid: int, dst: Optional[str] = None) -> bool:
        """Hand ONE completed-prefill slot to the decode pool: partial
        ``drain(slots=[slot])`` off the prefill replica → ``absorb()``
        on the chosen target, pages LUT-remapped, fleet id re-pointed,
        trace label re-attached (labels are engine-local, not wire
        state). Returns False when no target has capacity (the slot
        parks on the prefill replica and retries next step — admission
        backpressure, not loss). An absorb failure AFTER the drain is
        the handoff-in-flight crash: the request left the source with
        the snapshot, so it is orphaned through the journal and
        replayed like any dead-replica failover — replay routes back
        through the prefill pool and re-reaches this boundary with the
        ORIGINAL deadline."""
        se = self._replicas[src].engine
        need = se.pages_referenced([slot])
        entry = (self._journal.entry(frid)
                 if frid in self._journal else None)
        ctx = (list(entry.prompt) + list(entry.delivered)
               if entry is not None else [])
        if dst is None:
            dst = self._pick_decode_target(need, ctx)
            if dst is None:
                return False
        t0 = self._clock.monotonic()
        snap = se.drain(slots=[slot])
        try:
            mapping = self._replicas[dst].engine.absorb(snap)
        except Exception as e:  # noqa: BLE001 — orphan, never strand
            self._drop_placement(frid)
            if frid in self._journal:
                self._journal.reassign(frid, None, failover=True)
                self._place_orphans()
            else:
                self._lost += 1
                if self._metrics is not None:
                    self._c_lost.inc(replica=src)
            if self._tracer is not None:
                self._tracer.event(
                    "handoff_failed", lane="router",
                    rid=(entry.trace_id if entry is not None
                         and entry.trace_id is not None
                         else f"fleet-{frid}"),
                    src=src, dst=dst, reason=str(e))
            return False
        self._repoint(src, dst, mapping)
        t1 = self._clock.monotonic()
        self._handoffs += 1
        new_lrid = mapping.get(lrid)
        de = self._replicas[dst].engine
        if (entry is not None and entry.trace_id is not None
                and new_lrid is not None):
            de.label_request(new_lrid, entry.trace_id)
        if self._metrics is not None:
            self._c_handoffs.inc(src=src, dst=dst)
            self._metrics.histogram(
                FLEET_HANDOFF_DURATION,
                FLEET_HISTOGRAMS[FLEET_HANDOFF_DURATION]).observe(t1 - t0)
        rid_label = (entry.trace_id if entry is not None
                     and entry.trace_id is not None else f"fleet-{frid}")
        if self._tracer is not None:
            self._tracer.record(
                "handoff", t0, t1, lane="router", rid=rid_label,
                src=src, dst=dst, pages=need,
                delivered=(len(entry.delivered)
                           if entry is not None else 0))
        # Flight records on BOTH engines: the per-engine rings each
        # show their half of the migration, and the shared frid keys
        # them to the router span — one correlated timeline per
        # request across the pool boundary.
        for eng, kind, lr in ((se, "handoff_out", lrid),
                              (de, "handoff_in", new_lrid)):
            flight = getattr(eng, "_flight", None)
            if flight is not None:
                flight.record(kind, frid=frid, lrid=lr,
                              peer=(dst if kind == "handoff_out"
                                    else src), pages=need)
        return True

    def _auto_handoff(self) -> int:
        """Migrate every handoff-ready prefill-pool slot (prompt fully
        resident, first token emitted) to the decode pool; runs once
        per router step at the phase boundary. Capacity-refused slots
        stay parked and retry next step."""
        moved = 0
        for src in self._pools["prefill"]:
            rep = self._replicas[src]
            if rep.engine is None or not self._health.serving(src):
                continue
            for slot, lrid in rep.engine.handoff_ready_slots():
                frid = self._local.get((src, lrid))
                if frid is None:
                    continue             # not router-owned (warmup)
                if self._handoff_slot(src, slot, lrid, frid):
                    moved += 1
        return moved

    def handoff(self, frid: int, dst: Optional[str] = None) -> str:
        """Manually hand one fleet request prefill→decode (the
        auto-handoff in step() normally does this): returns the decode
        replica it landed on. Refuses requests that are mid-prefill
        (handoff is defined at the phase boundary only), already on the
        decode pool, or without a live placement."""
        if self._pools is None:
            raise FleetError("handoff requires Router(pools=...)")
        if frid not in self._where:
            raise FleetError(
                f"unknown or finished fleet request {frid}")
        src, lrid = self._where[frid]
        if self._pool_of.get(src) != "prefill":
            raise FleetError(
                f"fleet request {frid} is already on decode-pool "
                f"replica {src!r}")
        se = self._replicas[src].engine
        ready = {r: s for s, r in se.handoff_ready_slots()}
        if lrid not in ready:
            raise FleetError(
                f"fleet request {frid} is mid-prefill on {src!r}: "
                f"handoff moves only completed prefills (the phase "
                f"boundary)")
        if dst is not None:
            dst = str(dst)
            if self._pool_of.get(dst) != "decode":
                raise FleetError(
                    f"handoff target {dst!r} is not in the decode "
                    f"pool")
            rep = self._replica(dst)
            if rep.engine is None or not self._health.serving(dst):
                raise FleetError(
                    f"handoff target {dst!r} is not serving "
                    f"({self._health.state(dst)})")
        if not self._handoff_slot(src, ready[lrid], lrid, frid,
                                  dst=dst):
            raise FleetError(
                f"no decode-pool replica can absorb fleet request "
                f"{frid} right now (capacity precheck refused)")
        return self._where[frid][0]

    def pool_plan(self, policy: Optional[PoolPolicy] = None) -> PoolPlan:
        """Advisory autoscaling plan for the two pools, computed from
        the current summaries (fleet/pools.py): prefill scales OUT on
        queued prefill tokens, decode scales UP on free-page/slot
        watermarks. Pure and deterministic — the operator (or a test)
        decides what to do with it."""
        if self._pools is None:
            raise FleetError("pool_plan requires Router(pools=...)")
        return plan_pools(self.summaries(), self._pools,
                          policy or PoolPolicy())

    def shed(self, src: str, dst: str,
             slots: Optional[List[int]] = None,
             max_slots: Optional[int] = None) -> int:
        """Migrate active slots from replica ``src`` to ``dst``: partial
        ``drain(slots=...)`` → ``absorb()``, token-identically, with the
        fleet-id mapping re-pointed. Default slot choice is the first
        half of the active slots (sorted ids — deterministic); capacity
        is prechecked on the target (free slots AND free pages) so the
        shed either moves everything or moves nothing. Returns the
        number of migrated requests."""
        src, dst = str(src), str(dst)
        if src == dst:
            raise FleetError("shed needs two distinct replicas")
        if (self._pools is not None
                and self._pool_of.get(src) != self._pool_of.get(dst)):
            raise FleetError(
                f"shed cannot cross pools ({src!r} is "
                f"{self._pool_of.get(src)}, {dst!r} is "
                f"{self._pool_of.get(dst)}): the phase boundary moves "
                f"requests via handoff(), not load shedding")
        src_rep, dst_rep = self._replica(src), self._replica(dst)
        if src_rep.engine is None or not self._health.serving(src):
            raise FleetError(f"shed source {src!r} is not serving "
                             f"({self._health.state(src)})")
        if dst_rep.engine is None or not self._health.serving(dst):
            raise FleetError(f"shed target {dst!r} is not serving "
                             f"({self._health.state(dst)})")
        se, de = src_rep.engine, dst_rep.engine
        active = se.active_slot_ids()
        if slots is None:
            n = max(1, len(active) // 2)
            if max_slots is not None:
                n = min(n, int(max_slots))
            slots = active[:n]
        slots = sorted(int(s) for s in slots)
        if not slots:
            return 0
        dst_stats = de.replica_stats()
        free_slots = dst_stats["n_slots"] - dst_stats["active_slots"]
        need_pages = se.pages_referenced(slots)
        if len(slots) > free_slots or need_pages > dst_stats["pages_free"]:
            # Refuse up front: a drain the target cannot absorb would
            # strand the shed requests (they leave the source engine
            # with the snapshot). Tree-only pages on the target are
            # reclaimable, but the conservative check keeps shed
            # all-or-nothing without peeking into the peer's cache.
            raise FleetError(
                f"target {dst!r} cannot absorb {len(slots)} slots / "
                f"{need_pages} pages (free: {free_slots} slots, "
                f"{dst_stats['pages_free']} pages)")
        t0 = self._clock.monotonic()
        snap = se.drain(slots=slots)
        if self._metrics is not None:
            self._c_shed.inc(len(snap.slot_req), replica=str(src))
        mapping = de.absorb(snap)
        self._repoint(src, dst, mapping)
        if self._metrics is not None:
            self._c_migrated.inc(len(mapping), replica=str(dst))
        if self._tracer is not None:
            self._tracer.record(
                "fleet_shed", t0, self._clock.monotonic(), lane="router",
                src=str(src), dst=str(dst), slots=len(slots),
                requests=len(mapping))
        self.publish(str(src))
        self.publish(str(dst))
        return len(mapping)

    def maybe_shed(self) -> int:
        """Pressure-driven shed: when some replica's free-page fraction
        is below ``shed_free_frac`` and another's is above
        ``shed_target_free_frac``, move half the hot replica's active
        slots to the coldest peer (deterministic tiebreak by id).
        Returns migrated requests (0 when no pair qualifies or the
        conservative capacity precheck refuses)."""
        # Disaggregated fleets balance WITHIN each pool: shedding a
        # prefill-pool slot to a decode replica (or back) would cross
        # the phase boundary outside the handoff path.
        groups = ([list(self._replicas)] if self._pools is None
                  else [self._pools["prefill"], self._pools["decode"]])
        moved = 0
        for group in groups:
            stats = {rid: self._replicas[rid].engine.replica_stats()
                     for rid in group
                     if self._replicas[rid].engine is not None
                     and self._health.serving(rid)}

            def frac(st):
                return st["pages_free"] / st["pages_total"] \
                    if st["pages_total"] else 0.0

            hot = [r for r in sorted(stats)
                   if frac(stats[r]) < self.shed_free_frac
                   and stats[r]["active_slots"] > 1]
            cold = [r for r in sorted(stats)
                    if frac(stats[r]) > self.shed_target_free_frac]
            if not hot or not cold:
                continue
            src = min(hot, key=lambda r: (frac(stats[r]), r))
            dst = max(cold, key=lambda r: (frac(stats[r]), r))
            if src == dst:
                continue
            try:
                moved += self.shed(src, dst)
            except FleetError:
                continue             # no capacity this step; retry later
        return moved

    # -- introspection -----------------------------------------------------
    def stats(self) -> Dict[str, object]:
        """Router-level counters + per-replica aggregate prefix stats —
        what the fleet bench legs report."""
        per = {}
        hit = looked = 0.0
        for rid, rep in self._replicas.items():
            if rep.engine is None:
                per[rid] = {"state": self._health.state(rid)}
                continue
            pm = rep.engine.pool_metrics()
            hit += pm.get("prefix_hit_tokens", 0.0)
            looked += pm.get("prefix_lookup_tokens", 0.0)
            per[rid] = {
                "state": self._health.state(rid),
                "pages_free": pm.get("pages_free", 0.0),
                "active_slots": len(rep.engine.active_slot_ids()),
                "prefix_hit_tokens": pm.get("prefix_hit_tokens", 0.0),
                "prefix_lookup_tokens": pm.get("prefix_lookup_tokens",
                                               0.0),
                "requests_shed_total": pm.get("requests_shed_total", 0.0),
                "requests_resumed_total": pm.get("requests_resumed_total",
                                                 0.0),
            }
        return {
            "replicas": per,
            "pools": (None if self._pools is None
                      else {k: list(v) for k, v in self._pools.items()}),
            "handoffs": self._handoffs,
            "aggregate_prefix_hit_rate": hit / looked if looked else 0.0,
            "degraded_routes": self._degraded,
            "store_errors": self._store_errors,
            "health_states": self._health.counts(),
            "failovers": self._failovers,
            "replayed_tokens": self._replayed_tokens,
            "requests_lost": self._lost,
            "deadline_expired": self._expired,
            "journal_inflight": len(self._journal),
            "journal_delivered_tokens": self._journal.delivered_tokens_total,
        }
