"""Cache-aware fleet router — N serving replicas behind one admission
point.

One paged ``ContinuousBatcher`` is a replica, not a service; this module
is the fleet tier the ROADMAP's "millions of users" story needs. The
``Router`` fronts N in-process engine replicas and places each request
by SGLang-style cache-aware load balancing: every replica publishes a
:class:`~.summary.ReplicaSummary` (radix digest + pool watermarks +
per-phase p50s) into the registry, and admission scores

    score(replica) = (1 + prefix_match_len(prompt, digest))
                     × (eps + free_page_frac)
                     × (eps + free_slot_frac)
                     × 1 / (1 + decode_p50 / p50_ref)
                     × 1 / (1 + prefill_backlog / backlog_ref)

taking the argmax with a deterministic tiebreak (lowest replica id —
same summaries, same placement, always). The match term routes shared
system prompts to the replica that already holds their KV (prefill cost
scales with the novel suffix — PR 4); the load terms keep a cold cache
from losing every request to a hot one; the latency term is the
DistServe observation that decode-phase pressure (TPOT) is the thing
co-placement hurts, so it is scored per-phase rather than folded into a
scalar load average. The backlog term is the prefill-phase complement
(chunked prefill, PR 9): admitted-but-unfinished prefill tokens are
pressure the page/slot axes cannot see — a replica grinding through a
long prompt's chunks holds few extra slots, so without the discount a
long-prompt flood keeps landing on the same replica until its pool
finally fills. When summaries are STALE (an unreachable registry,
a wedged publisher — the bounded-retry clients of utils/retry.py fail
fast rather than hang) routing degrades to deterministic round-robin:
worse placement, zero additional risk.

The second half is LOAD SHEDDING: ``shed()`` takes a partial
``ServingSnapshot`` off a hot replica (``drain(slots=...)`` — a filter
over ``slot_req``, not a new format) and ``absorb()``s it into a cold
one, token-identically, re-pointing the router's fleet-level request
ids through the returned rid mapping. Both engines' flight recorders
log the handoff (``shed``/``absorb`` records), and
``assert_consistent`` holds on both pools afterwards.

Threading: the router is a single-threaded driver (one step loop owns
all N engines — the same model the per-engine step loop already uses);
the concurrent surface is the registry, whose client is thread-safe and
retry-bounded on its own.
"""
from __future__ import annotations

from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence, Tuple

from ..metrics.exporter import (
    FLEET_AFFINITY_HITS_TOTAL, FLEET_COUNTERS, FLEET_MIGRATED_TOTAL,
    FLEET_ROUTED_TOTAL, FLEET_SHED_TOTAL, export_serving_pool,
)
from ..models.snapshot import SnapshotError, check_fingerprint
from ..obs import SYSTEM_CLOCK
from .summary import (
    MemoryStore, ReplicaSummary, list_summaries, prefix_match_len,
    publish_summary, summarize,
)

# Phases feeding the routing p50s (the names _obs_span records).
_DECODE_PHASES = ("decode_chunk", "verify")
_PREFILL_PHASES = ("prefill", "prefill_chunk")


class FleetError(RuntimeError):
    """Fleet-level misuse or impossible operation (unknown replica,
    shed without capacity, heterogeneous fleet)."""


def _p50(window) -> float:
    if not window:
        return 0.0
    xs = sorted(window)
    return xs[len(xs) // 2]


class _Replica:
    """Router-side state for one engine: identity, publish seq, and the
    rolling phase-duration windows the summary p50s are computed from
    (fed by the same ``pool_metrics()`` phase batch the Prometheus
    export consumes — drained once, used twice)."""

    def __init__(self, replica_id: str, engine) -> None:
        self.id = replica_id
        self.engine = engine
        self.seq = 0
        self.decode_window: deque = deque(maxlen=256)
        self.prefill_window: deque = deque(maxlen=64)


class Router:
    """Admission front for N in-process paged engine replicas.

    ``replicas`` is a sequence of ``(id, ContinuousBatcher)`` pairs (ids
    unique; engines paged with one shared page_size — scoring compares
    page-aligned match lengths across them). ``store`` is the summary
    plane: any object with the registry client's get/set/get_keys
    (+mget) subset — defaults to an in-process :class:`MemoryStore`;
    pass the real registry ``Client`` to share summaries across
    processes. ``policy`` is ``"affinity"`` (cache-aware scoring, the
    point of this module) or ``"round_robin"`` (the baseline the bench
    leg beats). ``metrics`` is an optional metrics.exporter ``Registry``
    — when present every replica's ``pool_metrics()`` exports under a
    ``{replica=}`` label and the ``tpu_fleet_*`` counters are kept.
    """

    def __init__(self, replicas: Sequence[Tuple[str, object]],
                 store=None, fleet: str = "fleet",
                 policy: str = "affinity", stale_s: float = 5.0,
                 clock=None, tracer=None, metrics=None,
                 digest_top_k: int = 8, digest_max_tokens: int = 512,
                 p50_ref_s: float = 0.05, load_eps: float = 0.1,
                 backlog_ref_tokens: float = 2048.0,
                 auto_shed: bool = False,
                 shed_free_frac: float = 0.125,
                 shed_target_free_frac: float = 0.5) -> None:
        if not replicas:
            raise FleetError("a fleet needs at least one replica")
        if policy not in ("affinity", "round_robin"):
            raise FleetError(
                f"policy must be 'affinity' or 'round_robin', got "
                f"{policy!r}")
        self._replicas: "OrderedDict[str, _Replica]" = OrderedDict()
        first_id: Optional[str] = None
        for rid, eng in replicas:
            rid = str(rid)
            if rid in self._replicas:
                raise FleetError(f"duplicate replica id {rid!r}")
            eng.replica_stats()          # paged-layout gate, fails early
            if first_id is None:
                first_id = rid
            else:
                # Fingerprint compatibility is validated HERE, not at
                # shed time: a partial drain removes the shed slots
                # from the source BEFORE absorb() runs its own
                # fingerprint check, so a mismatched pair discovered
                # mid-shed would strand the drained requests. With a
                # homogeneous fleet (everything but n_pages must
                # match — snapshot.check_fingerprint), absorb can only
                # refuse for capacity, which shed() prechecks.
                try:
                    check_fingerprint(
                        self._replicas[first_id].engine.fingerprint(),
                        eng.fingerprint())
                except SnapshotError as e:
                    raise FleetError(
                        f"replica {rid!r} is not shed-compatible with "
                        f"{first_id!r}: {e}") from e
            self._replicas[rid] = _Replica(rid, eng)
        self.page_size = int(
            self._replicas[first_id].engine.replica_stats()["page_size"])
        self.fleet = str(fleet)
        self.policy = policy
        self.stale_s = float(stale_s)
        self._store = store if store is not None else MemoryStore()
        self._clock = clock or SYSTEM_CLOCK
        self._tracer = tracer
        self._metrics = metrics
        self.digest_top_k = int(digest_top_k)
        self.digest_max_tokens = int(digest_max_tokens)
        self.p50_ref_s = float(p50_ref_s)
        self.load_eps = float(load_eps)
        self.backlog_ref_tokens = float(backlog_ref_tokens)
        self.auto_shed = bool(auto_shed)
        self.shed_free_frac = float(shed_free_frac)
        self.shed_target_free_frac = float(shed_target_free_frac)
        if metrics is not None:
            self._c_routed = metrics.counter(
                FLEET_ROUTED_TOTAL, FLEET_COUNTERS[FLEET_ROUTED_TOTAL])
            self._c_shed = metrics.counter(
                FLEET_SHED_TOTAL, FLEET_COUNTERS[FLEET_SHED_TOTAL])
            self._c_migrated = metrics.counter(
                FLEET_MIGRATED_TOTAL, FLEET_COUNTERS[FLEET_MIGRATED_TOTAL])
            self._c_affinity = metrics.counter(
                FLEET_AFFINITY_HITS_TOTAL,
                FLEET_COUNTERS[FLEET_AFFINITY_HITS_TOTAL])
        # Fleet-level request ids: one namespace over all replicas —
        # local engine ids are replica-private and CHANGE on migration
        # (absorb assigns fresh ones), so callers hold fleet ids and the
        # router re-points the mapping at each shed.
        self._next_frid = 0
        self._where: Dict[int, Tuple[str, int]] = {}   # frid -> (rid, lrid)
        self._local: Dict[Tuple[str, int], int] = {}   # (rid, lrid) -> frid
        self._req_metrics: Dict[int, Dict[str, float]] = {}
        self._rr = 0                                   # round-robin cursor
        self._degraded = 0                             # degraded routes
        self._store_errors = 0
        # Parsed-summary cache, valid for one publish cycle: routing a
        # burst of submits between steps re-reads/re-parses nothing —
        # publish() (the only writer this router knows about)
        # invalidates it, so a shared-registry peer's update is picked
        # up at the next publish boundary at the latest.
        self._summaries_cache: Optional[Dict[str, ReplicaSummary]] = None
        self.publish()                                 # summaries exist

    # -- summary plane -----------------------------------------------------
    def publish(self, replica_id: Optional[str] = None) -> None:
        """Publish summaries (one replica, or the whole fleet): drain
        each engine's ``pool_metrics()`` once — feeding the rolling
        phase windows AND, when a metrics registry is attached, the
        ``{replica=}``-labeled Prometheus export — then write the
        summary to the store. Store failures are counted and swallowed:
        the registry client is retry-bounded, and an unreachable
        summary plane must degrade routing, never kill serving."""
        reps = ([self._replica(replica_id)] if replica_id is not None
                else list(self._replicas.values()))
        for rep in reps:
            pm = rep.engine.pool_metrics()
            for phase, seconds in pm.get("phase_durations") or ():
                if phase in _DECODE_PHASES:
                    rep.decode_window.append(float(seconds))
                elif phase in _PREFILL_PHASES:
                    rep.prefill_window.append(float(seconds))
            if self._metrics is not None:
                export_serving_pool(self._metrics, pm,
                                    labels={"replica": rep.id})
            rep.seq += 1
            s = summarize(
                rep.engine, rep.id, fleet=self.fleet, seq=rep.seq,
                now_wall=self._clock.wall(),
                decode_p50_s=_p50(rep.decode_window),
                prefill_p50_s=_p50(rep.prefill_window),
                top_k=self.digest_top_k,
                max_tokens=self.digest_max_tokens)
            try:
                publish_summary(self._store, s)
            except Exception:  # noqa: BLE001 — summary plane down ≠ serving down
                self._store_errors += 1
        self._summaries_cache = None       # next route() re-reads once

    def summaries(self) -> Dict[str, ReplicaSummary]:
        """Summaries for THIS fleet's known replicas, from the store
        (an empty dict when the store is unreachable — the caller's
        staleness check then degrades routing). Cached per publish
        cycle: the store is read/parsed once per step, not once per
        submit."""
        if self._summaries_cache is not None:
            return dict(self._summaries_cache)
        try:
            listed = list_summaries(self._store, self.fleet)
        except Exception:  # noqa: BLE001 — summary plane down ≠ serving down
            self._store_errors += 1
            return {}
        out = {r: s for r, s in listed.items() if r in self._replicas}
        self._summaries_cache = out
        return dict(out)

    # -- scoring -----------------------------------------------------------
    def score(self, summary: ReplicaSummary,
              prompt: Sequence[int]) -> Tuple[float, int]:
        """(score, prefix match tokens) for placing ``prompt`` on the
        summarized replica — a pure function of its arguments, which is
        what makes placement deterministic and testable."""
        match = prefix_match_len(prompt, summary.digest, self.page_size)
        eps = self.load_eps
        load = ((eps + summary.free_frac)
                * (eps + summary.free_slot_frac)
                / (1.0 + summary.decode_p50_s / self.p50_ref_s)
                / (1.0 + max(0, summary.prefill_backlog_tokens)
                   / self.backlog_ref_tokens))
        return (1.0 + match) * load, match

    def route(self, prompt: Sequence[int]) -> Tuple[str, str, int]:
        """Choose a replica for ``prompt``: returns
        ``(replica id, policy used, prefix match tokens)``. Affinity
        scoring needs FRESH summaries (published within ``stale_s`` of
        now); with none fresh — or under ``policy="round_robin"`` — the
        deterministic round-robin fallback places the request instead
        (bounded staleness can degrade placement quality, never
        correctness)."""
        if self.policy == "affinity":
            now = self._clock.wall()
            fresh = {r: s for r, s in self.summaries().items()
                     if now - s.published_wall <= self.stale_s}
            if fresh:
                best_rid, best_score, best_match = None, 0.0, 0
                for rid in sorted(fresh):
                    sc, match = self.score(fresh[rid], prompt)
                    if best_rid is None or sc > best_score:
                        best_rid, best_score, best_match = rid, sc, match
                return best_rid, "affinity", best_match
            self._degraded += 1
        ids = list(self._replicas)
        rid = ids[self._rr % len(ids)]
        self._rr += 1
        return rid, ("round_robin" if self.policy == "round_robin"
                     else "degraded"), 0

    # -- serving API -------------------------------------------------------
    def submit(self, prompt, max_new: int,
               trace_id: Optional[str] = None) -> int:
        """Route and admit one request; returns its FLEET id (stable
        across migrations — local engine ids are not)."""
        prompt = [int(t) for t in prompt]
        rid, policy, match = self.route(prompt)
        eng = self._replica(rid).engine
        lrid = eng.submit(prompt, max_new=max_new, trace_id=trace_id)
        frid = self._next_frid
        self._next_frid += 1
        self._where[frid] = (rid, lrid)
        self._local[(rid, lrid)] = frid
        if self._metrics is not None:
            self._c_routed.inc(replica=rid, policy=policy)
            if match:
                self._c_affinity.inc(replica=rid)
        if self._tracer is not None:
            self._tracer.event(
                "route", lane="router",
                rid=trace_id if trace_id is not None else f"fleet-{frid}",
                replica=rid, policy=policy, match_tokens=match)
        return frid

    def locate(self, frid: int) -> Tuple[str, int]:
        """(replica id, local request id) a fleet request currently
        lives on — moves when a shed migrates it."""
        if frid not in self._where:
            raise FleetError(f"unknown or finished fleet request {frid}")
        return self._where[frid]

    @property
    def pending(self) -> int:
        return sum(r.engine.pending for r in self._replicas.values())

    def step(self) -> Dict[int, list]:
        """Step every replica once (admission + one decode/verify chunk
        each), refresh the published summaries, and return the newly
        finished streams keyed by FLEET id. With ``auto_shed`` on, a
        replica past the pressure watermark sheds toward the coldest
        peer after the step."""
        done: Dict[int, list] = {}
        for rep in self._replicas.values():
            if not rep.engine.pending:
                continue
            finished = rep.engine.step()
            metrics = rep.engine.pop_request_metrics()
            for lrid, toks in finished.items():
                frid = self._local.pop((rep.id, lrid), None)
                if frid is None:
                    continue                 # not router-owned (warmup)
                self._where.pop(frid, None)
                done[frid] = toks
                if lrid in metrics:
                    self._req_metrics[frid] = metrics[lrid]
        self.publish()
        if self.auto_shed:
            self.maybe_shed()
        return done

    def run(self) -> Dict[int, list]:
        """Drain everything submitted across the fleet."""
        done: Dict[int, list] = {}
        while self.pending:
            done.update(self.step())
        return done

    def pop_request_metrics(self) -> Dict[int, Dict[str, float]]:
        """Per-request latency records (ttft_s/latency_s/tokens) keyed
        by fleet id, drained since the last call — migration-safe: a
        shed request's record closes on the replica that finished it,
        with the handoff gap charged (absorb rebases the clocks)."""
        out, self._req_metrics = self._req_metrics, {}
        return out

    # -- load shedding -----------------------------------------------------
    def _replica(self, rid: str) -> _Replica:
        try:
            return self._replicas[str(rid)]
        except KeyError:
            raise FleetError(f"unknown replica {rid!r}") from None

    def shed(self, src: str, dst: str,
             slots: Optional[List[int]] = None,
             max_slots: Optional[int] = None) -> int:
        """Migrate active slots from replica ``src`` to ``dst``: partial
        ``drain(slots=...)`` → ``absorb()``, token-identically, with the
        fleet-id mapping re-pointed. Default slot choice is the first
        half of the active slots (sorted ids — deterministic); capacity
        is prechecked on the target (free slots AND free pages) so the
        shed either moves everything or moves nothing. Returns the
        number of migrated requests."""
        if str(src) == str(dst):
            raise FleetError("shed needs two distinct replicas")
        se, de = self._replica(src).engine, self._replica(dst).engine
        active = se.active_slot_ids()
        if slots is None:
            n = max(1, len(active) // 2)
            if max_slots is not None:
                n = min(n, int(max_slots))
            slots = active[:n]
        slots = sorted(int(s) for s in slots)
        if not slots:
            return 0
        dst_stats = de.replica_stats()
        free_slots = dst_stats["n_slots"] - dst_stats["active_slots"]
        need_pages = se.pages_referenced(slots)
        if len(slots) > free_slots or need_pages > dst_stats["pages_free"]:
            # Refuse up front: a drain the target cannot absorb would
            # strand the shed requests (they leave the source engine
            # with the snapshot). Tree-only pages on the target are
            # reclaimable, but the conservative check keeps shed
            # all-or-nothing without peeking into the peer's cache.
            raise FleetError(
                f"target {dst!r} cannot absorb {len(slots)} slots / "
                f"{need_pages} pages (free: {free_slots} slots, "
                f"{dst_stats['pages_free']} pages)")
        t0 = self._clock.monotonic()
        snap = se.drain(slots=slots)
        if self._metrics is not None:
            self._c_shed.inc(len(snap.slot_req), replica=str(src))
        mapping = de.absorb(snap)
        moved = 0
        for (rid, lrid), frid in list(self._local.items()):
            if rid == str(src) and lrid in mapping:
                del self._local[(rid, lrid)]
                new_key = (str(dst), mapping[lrid])
                self._local[new_key] = frid
                self._where[frid] = new_key
                moved += 1
        if self._metrics is not None:
            self._c_migrated.inc(len(mapping), replica=str(dst))
        if self._tracer is not None:
            self._tracer.record(
                "fleet_shed", t0, self._clock.monotonic(), lane="router",
                src=str(src), dst=str(dst), slots=len(slots),
                requests=len(mapping))
        self.publish(str(src))
        self.publish(str(dst))
        return len(mapping)

    def maybe_shed(self) -> int:
        """Pressure-driven shed: when some replica's free-page fraction
        is below ``shed_free_frac`` and another's is above
        ``shed_target_free_frac``, move half the hot replica's active
        slots to the coldest peer (deterministic tiebreak by id).
        Returns migrated requests (0 when no pair qualifies or the
        conservative capacity precheck refuses)."""
        stats = {rid: rep.engine.replica_stats()
                 for rid, rep in self._replicas.items()}

        def frac(st):
            return st["pages_free"] / st["pages_total"] \
                if st["pages_total"] else 0.0

        hot = [r for r in sorted(stats)
               if frac(stats[r]) < self.shed_free_frac
               and stats[r]["active_slots"] > 1]
        cold = [r for r in sorted(stats)
                if frac(stats[r]) > self.shed_target_free_frac]
        if not hot or not cold:
            return 0
        src = min(hot, key=lambda r: (frac(stats[r]), r))
        dst = max(cold, key=lambda r: (frac(stats[r]), r))
        if src == dst:
            return 0
        try:
            return self.shed(src, dst)
        except FleetError:
            return 0                 # no capacity this step; retry later

    # -- introspection -----------------------------------------------------
    def stats(self) -> Dict[str, object]:
        """Router-level counters + per-replica aggregate prefix stats —
        what the fleet bench leg reports."""
        per = {}
        hit = looked = 0.0
        for rid, rep in self._replicas.items():
            pm = rep.engine.pool_metrics()
            hit += pm.get("prefix_hit_tokens", 0.0)
            looked += pm.get("prefix_lookup_tokens", 0.0)
            per[rid] = {
                "pages_free": pm.get("pages_free", 0.0),
                "active_slots": len(rep.engine.active_slot_ids()),
                "prefix_hit_tokens": pm.get("prefix_hit_tokens", 0.0),
                "prefix_lookup_tokens": pm.get("prefix_lookup_tokens",
                                               0.0),
                "requests_shed_total": pm.get("requests_shed_total", 0.0),
                "requests_resumed_total": pm.get("requests_resumed_total",
                                                 0.0),
            }
        return {
            "replicas": per,
            "aggregate_prefix_hit_rate": hit / looked if looked else 0.0,
            "degraded_routes": self._degraded,
            "store_errors": self._store_errors,
        }
