"""Pool sizing policy for disaggregated serving — the two pools scale
in DIFFERENT units.

DistServe's observation is that prefill and decode saturate different
resources: prefill is compute-bound and embarrassingly parallel across
requests (more replicas = more prompts in flight), decode is
capacity-bound on KV residency (more pages/slots per replica = more
concurrent streams, and a bigger batch per chip). So the prefill pool
scales OUT — the plan's unit is a REPLICA COUNT, driven by the queued
prefill tokens the summaries already publish
(``prefill_backlog_tokens``, PR 9) — while the decode pool scales UP:
the unit is PAGES PER REPLICA, driven by the free-page/free-slot
watermarks (the same signals auto-shed balances on, read here as a
capacity deficit instead of an imbalance).

Everything in this module is a pure function of published summaries:
deterministic, testable, and ADVISORY — the in-process fleet cannot
spawn replicas, so :meth:`Router.pool_plan` returns the plan and the
operator (or the cross-process deployment layer, the ROADMAP
follow-on) acts on it.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Sequence, Tuple

from .summary import ReplicaSummary

__all__ = ["PoolPolicy", "PoolPlan", "plan_pools"]


@dataclass(frozen=True)
class PoolPolicy:
    """Knobs for :func:`plan_pools`.

    ``prefill_tokens_per_replica`` is the backlog one prefill replica
    is expected to chew through within SLO — desired prefill replicas =
    ceil(total backlog / this). ``decode_free_page_frac_low`` /
    ``decode_free_slot_frac_low`` are the watermarks below which the
    decode pool is declared capacity-starved; ``decode_page_headroom``
    is the pool-size multiplier the plan then asks for."""

    prefill_tokens_per_replica: int = 4096
    decode_free_page_frac_low: float = 0.15
    decode_free_slot_frac_low: float = 0.25
    decode_page_headroom: float = 2.0

    def __post_init__(self) -> None:
        if self.prefill_tokens_per_replica < 1:
            raise ValueError(
                f"prefill_tokens_per_replica must be >= 1, got "
                f"{self.prefill_tokens_per_replica}")
        for name in ("decode_free_page_frac_low",
                     "decode_free_slot_frac_low"):
            v = getattr(self, name)
            if not 0.0 <= v < 1.0:
                raise ValueError(f"{name} must be in [0, 1), got {v}")
        if self.decode_page_headroom < 1.0:
            raise ValueError(
                f"decode_page_headroom must be >= 1.0, got "
                f"{self.decode_page_headroom}")


@dataclass(frozen=True)
class PoolPlan:
    """One advisory sizing decision, in each pool's own unit."""

    prefill_replicas: int            # currently summarized
    prefill_replicas_desired: int    # scale-OUT target (replica count)
    prefill_backlog_tokens: int      # fleet-wide queued prefill tokens
    decode_replicas: int             # currently summarized
    decode_scale_up: bool            # below a capacity watermark?
    decode_pages_total: int          # pool pages across decode replicas
    decode_pages_desired: int        # scale-UP target (pages)
    reasons: Tuple[str, ...]         # human-readable derivation


def plan_pools(summaries: Dict[str, ReplicaSummary],
               pools: Dict[str, Sequence[str]],
               policy: PoolPolicy = PoolPolicy()) -> PoolPlan:
    """Size the two pools from published summaries — pure and
    deterministic (same summaries, same plan). Replicas without a
    summary (dead, or the plane dropped a write) simply don't
    contribute: the plan is computed over what is OBSERVED, the same
    bounded-staleness posture routing takes."""
    reasons = []
    pre = [summaries[r] for r in pools["prefill"] if r in summaries]
    dec = [summaries[r] for r in pools["decode"] if r in summaries]

    backlog = sum(max(0, int(s.prefill_backlog_tokens)) for s in pre)
    desired = max(1, math.ceil(
        backlog / policy.prefill_tokens_per_replica))
    if desired > len(pre):
        reasons.append(
            f"prefill: {backlog} backlog tokens need {desired} "
            f"replicas at {policy.prefill_tokens_per_replica} "
            f"tokens/replica (have {len(pre)})")
    else:
        reasons.append(
            f"prefill: {backlog} backlog tokens fit "
            f"{len(pre)} replicas")

    pages_total = sum(int(s.pages_total) for s in dec)
    scale_up = False
    for s in dec:
        if s.free_frac < policy.decode_free_page_frac_low:
            scale_up = True
            reasons.append(
                f"decode: {s.replica} free-page frac "
                f"{s.free_frac:.3f} < "
                f"{policy.decode_free_page_frac_low}")
        if s.free_slot_frac < policy.decode_free_slot_frac_low:
            scale_up = True
            reasons.append(
                f"decode: {s.replica} free-slot frac "
                f"{s.free_slot_frac:.3f} < "
                f"{policy.decode_free_slot_frac_low}")
    pages_desired = (math.ceil(pages_total * policy.decode_page_headroom)
                     if scale_up else pages_total)
    if not scale_up:
        reasons.append("decode: above both watermarks")
    return PoolPlan(
        prefill_replicas=len(pre),
        prefill_replicas_desired=desired,
        prefill_backlog_tokens=backlog,
        decode_replicas=len(dec),
        decode_scale_up=scale_up,
        decode_pages_total=pages_total,
        decode_pages_desired=pages_desired,
        reasons=tuple(reasons),
    )
