"""Inventory scraping — exec the native prober, parse its JSON.

Parity with the scrape half of the reference agent (parse_smi_uuids.py:6-18
execs ``nvidia-smi -L`` and regexes UUIDs). The seam is the binary path /
fake-metrics file, so everything is testable without TPU hardware
(SURVEY.md hard part f)."""
from __future__ import annotations

import json
import os
import subprocess
from typing import List, Optional

from ..registry.inventory import ChipInfo

_HERE = os.path.dirname(os.path.abspath(__file__))


def probe_binary_path() -> str:
    """Default location of the built prober (make -C native/tpuprobe)."""
    return os.environ.get(
        "TPUPROBE_BIN",
        os.path.join(_HERE, "..", "..", "native", "tpuprobe", "tpuprobe"),
    )


class Scraper:
    def __init__(self, binary: Optional[str] = None, fake_file: Optional[str] = None,
                 timeout_s: float = 5.0, device_plugin=None):
        """``device_plugin``: optional agent.deviceplugin.DevicePluginSource
        (or env TPU_DEVICE_PLUGIN_URL) overlaying live duty-cycle/HBM onto
        the prober's inventory — the prober knows which chips exist
        (/dev/accel*), the device-plugin endpoint knows how busy they are
        (VERDICT.md r3 missing #2: without this, real nodes publish zeros
        and utilization scoring degenerates to a constant)."""
        self.binary = binary or probe_binary_path()
        self.fake_file = fake_file or os.environ.get("TPUPROBE_FAKE")
        self.timeout_s = timeout_s
        if device_plugin is None:
            url = os.environ.get("TPU_DEVICE_PLUGIN_URL", "")
            if url:
                from .deviceplugin import DevicePluginSource

                device_plugin = DevicePluginSource(url)
        self.device_plugin = device_plugin

    def scrape(self) -> List[ChipInfo]:
        """One probe → chip list. Raises RuntimeError when the prober is
        missing or emits garbage (the agent loop logs and retries — the
        reference's loop just re-execs every 2 s)."""
        argv = [self.binary, "--once"]
        if self.fake_file:
            argv += ["--fake", self.fake_file]
        try:
            proc = subprocess.run(
                argv, capture_output=True, timeout=self.timeout_s, check=False
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            raise RuntimeError(f"tpuprobe failed: {e}") from e
        if proc.returncode != 0:
            # exit 1 = probe found NO devices (tpuprobe.cpp) — a broken node
            # must not masquerade as a fully idle one (utilization 0 would
            # make it the top-scored target).
            raise RuntimeError(
                f"tpuprobe exit {proc.returncode}: {proc.stderr.decode()!r}"
            )
        try:
            doc = json.loads(proc.stdout.decode() or "{}")
        except ValueError as e:
            raise RuntimeError(f"tpuprobe emitted non-JSON: {proc.stdout!r}") from e
        chips = []
        for c in doc.get("chips", []):
            chips.append(ChipInfo(
                device_id=int(c.get("device_id", 0)),
                duty_cycle=float(c.get("duty_cycle", 0.0)),
                hbm_used_bytes=int(c.get("hbm_used", 0)),
                hbm_total_bytes=int(c.get("hbm_total", 0)),
            ))
        if self.device_plugin is not None and chips:
            from .deviceplugin import overlay

            overlay(chips, self.device_plugin.read())
        return chips
