"""Live utilization from the node's TPU device-plugin metrics endpoint.

On a real GKE TPU node, per-chip utilization is published by the GKE
tpu-device-plugin / libtpu exporter as a Prometheus text endpoint on
localhost (duty cycle %, HBM bytes used/total, labeled by accelerator id).
The r3 live path had no consumer for it: tpuprobe.cpp enumerates
/dev/accel* but reports duty_cycle=0/hbm=0 (the device files don't carry
utilization), so on real hardware every node scored as idle — VERDICT.md r3
missing #2. This module is the third probe source: the agent overlays these
live numbers onto the prober's chip inventory before publishing.

The parser accepts both the GKE device-plugin names (``duty_cycle``,
``memory_used``, ``memory_total``, ``tensorcore_utilization`` with an
``accelerator_id`` label ending in ``-<device>``) and our own re-exported
names (metrics/client.py TPU_SERIES with a ``device_id`` label), so an
agent can also scrape a peer agent's exporter — no reference analogue (the
reference's live source is dcgm-exporter scraped by a separate Prometheus,
pkg/prom/fetch_prom_metrics/prom_metrics.go:63-70).
"""
from __future__ import annotations

import logging
import re
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

log = logging.getLogger(__name__)

# Metric-name synonyms: GKE device-plugin convention first, our re-exported
# series (metrics/client.py) second.
DUTY_NAMES = ("duty_cycle", "tpu_duty_cycle_percent")
HBM_USED_NAMES = ("memory_used", "tpu_hbm_memory_usage_bytes")
HBM_TOTAL_NAMES = ("memory_total", "tpu_hbm_memory_total_bytes")
TENSORCORE_NAMES = ("tensorcore_utilization", "tpu_tensorcore_utilization")

_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[^\s]+)"
)
_LABEL = re.compile(r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:\\.|[^"\\])*)"')


def parse_prom_text(text: str) -> Iterator[Tuple[str, Dict[str, str], float]]:
    """Minimal Prometheus text-format parser: (name, labels, value) per
    sample line; comments/HELP/TYPE and malformed lines are skipped."""
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE.match(line)
        if m is None:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = {
            lm.group("k"): lm.group("v").replace('\\"', '"')
            for lm in _LABEL.finditer(m.group("labels") or "")
        }
        yield m.group("name"), labels, value


def device_index(labels: Dict[str, str]) -> Optional[int]:
    """Chip index within the host from sample labels: explicit
    ``device_id``/``chip`` first, else the trailing ``-<n>`` of the GKE
    ``accelerator_id`` (e.g. ``4804277629165885214-3`` → 3)."""
    for key in ("device_id", "chip"):
        raw = labels.get(key)
        if raw is not None and raw.isdigit():
            return int(raw)
    acc = labels.get("accelerator_id", "")
    if "-" in acc:
        tail = acc.rsplit("-", 1)[1]
        if tail.isdigit():
            return int(tail)
    return None


@dataclass
class ChipMetrics:
    duty_cycle: float = 0.0        # 0..1
    hbm_used_bytes: int = 0
    hbm_total_bytes: int = 0
    tensorcore_util: float = 0.0   # 0..1


class DevicePluginSource:
    """Scrapes one metrics endpoint into per-chip metrics."""

    def __init__(self, url: str, timeout_s: float = 2.0) -> None:
        self.url = url
        self.timeout_s = timeout_s

    def fetch_text(self) -> str:
        with urllib.request.urlopen(self.url, timeout=self.timeout_s) as r:
            return r.read().decode(errors="replace")

    def read(self) -> Dict[int, ChipMetrics]:
        """One scrape → {device index → metrics}. Unreachable endpoint or
        unparsable payload returns {} (the agent degrades to prober-only
        inventory — observability must never break publishing)."""
        try:
            text = self.fetch_text()
        except (urllib.error.URLError, OSError, ValueError) as e:
            log.debug("device-plugin endpoint %s unreachable: %s", self.url, e)
            return {}
        out: Dict[int, ChipMetrics] = {}
        has_duty = set()
        for name, labels, value in parse_prom_text(text):
            idx = device_index(labels)
            if idx is None:
                continue
            cm = out.setdefault(idx, ChipMetrics())
            if name in DUTY_NAMES:
                # Both conventions report percent 0..100.
                cm.duty_cycle = max(0.0, min(1.0, value / 100.0))
                has_duty.add(idx)
            elif name in HBM_USED_NAMES:
                cm.hbm_used_bytes = int(value)
            elif name in HBM_TOTAL_NAMES:
                cm.hbm_total_bytes = int(value)
            elif name in TENSORCORE_NAMES:
                cm.tensorcore_util = max(0.0, min(1.0, value / 100.0))
        # An endpoint exporting only tensorcore_utilization (some libtpu
        # exporter versions) must still drive scoring — without the
        # fallback such nodes publish duty 0 and score as idle, the exact
        # defect this module exists to fix.
        for idx, cm in out.items():
            if idx not in has_duty and cm.tensorcore_util > 0.0:
                cm.duty_cycle = cm.tensorcore_util
        return out


def overlay(chips: List, metrics: Dict[int, ChipMetrics]) -> None:
    """Merge live endpoint metrics into prober ChipInfos in place. The
    prober owns chip EXISTENCE (device files); the endpoint owns
    utilization — its numbers win whenever its index matches a probed
    chip."""
    for chip in chips:
        cm = metrics.get(chip.device_id)
        if cm is None:
            continue
        chip.duty_cycle = cm.duty_cycle
        if cm.hbm_used_bytes:
            chip.hbm_used_bytes = cm.hbm_used_bytes
        if cm.hbm_total_bytes:
            chip.hbm_total_bytes = cm.hbm_total_bytes
