"""Node agent — per-host inventory/utilization publisher (C14-C16 parity).

The reference's DaemonSet loop (pkg/profiler/profile_gpu.sh:3-13) scrapes
``nvidia-smi -L`` every 2 s and pipes changed UUID sets into a Go publisher
that writes Redis (cmd/client/client.go:24-79). Ours scrapes the native
``tpuprobe`` binary (native/tpuprobe — the C++ obligation the reference left
dead) and publishes a TYPED ``NodeInventory`` (chips, topology labels,
utilization) to the registry, still on change-detection with a periodic
heartbeat refresh.
"""
from .scrape import Scraper, probe_binary_path
from .publisher import Publisher

__all__ = ["Scraper", "Publisher", "probe_binary_path"]
