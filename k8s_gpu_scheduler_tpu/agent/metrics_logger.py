"""Offline chip-metrics logger — C18 parity.

The reference ships a standalone tool
(/root/reference/pkg/profiler/parse_smi_metrics.py:25-42) that polls
``nvidia-smi --query-gpu=power.draw,utilization.gpu,temperature.gpu`` once
a second into a pandas frame and dumps it as TSV on SIGINT — an ad-hoc
profiling aid, commented out of the agent loop (profile_gpu.sh:9). This is
its TPU-native analogue: poll the native prober (the same seam the agent
uses, agent/scrape.py) for per-chip MXU duty cycle and HBM occupancy, keep
rows in memory, write a TSV on SIGINT/SIGTERM or when ``--samples`` runs
out. No pandas needed — a list of tuples and one write.

Usage (the reference's shape):
    python -m k8s_gpu_scheduler_tpu.agent.metrics_logger [-o chip_metrics.tsv]
        [--interval 1.0] [--samples N]   # Ctrl-C to stop and dump
"""
from __future__ import annotations

import argparse
import signal
import sys
import time
from typing import List, Tuple

from .scrape import Scraper

COLUMNS = ("timestamp", "device_id", "duty_cycle", "hbm_used_bytes",
           "hbm_total_bytes")


class MetricsLogger:
    def __init__(self, scraper: Scraper, out_path: str,
                 interval_s: float = 1.0) -> None:
        self.scraper = scraper
        self.out_path = out_path
        self.interval_s = interval_s
        self.rows: List[Tuple] = []
        self._stop = False

    def sample_once(self) -> int:
        """Poll once; append one row per chip. Returns chips seen."""
        now = time.time()
        chips = self.scraper.scrape()
        for c in chips:
            self.rows.append((now, c.device_id, c.duty_cycle,
                              c.hbm_used_bytes, c.hbm_total_bytes))
        return len(chips)

    def dump(self) -> str:
        """Write the accumulated samples as TSV (the reference dumps its
        frame with to_csv(sep='\\t') on SIGINT)."""
        with open(self.out_path, "w") as f:
            f.write("\t".join(COLUMNS) + "\n")
            for row in self.rows:
                f.write("\t".join(
                    f"{v:.6f}" if isinstance(v, float) else str(v)
                    for v in row) + "\n")
        return self.out_path

    def run(self, max_samples: int = 0) -> None:
        taken = 0
        while not self._stop and (not max_samples or taken < max_samples):
            try:
                self.sample_once()
            except RuntimeError as e:
                print(f"sample failed: {e}", file=sys.stderr, flush=True)
            taken += 1
            if max_samples and taken >= max_samples:
                break
            time.sleep(self.interval_s)

    def request_stop(self, *_args) -> None:
        self._stop = True


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpu-metrics-logger",
        description="poll the TPU prober into a TSV (SIGINT dumps and exits)")
    parser.add_argument("-o", "--out", default="chip_metrics.tsv")
    parser.add_argument("--interval", type=float, default=1.0)
    parser.add_argument("--samples", type=int, default=0,
                        help="stop after N samples (0 = until SIGINT)")
    parser.add_argument("--fake", default=None,
                        help="fake metrics file for the prober (test seam)")
    args = parser.parse_args(argv)

    logger = MetricsLogger(Scraper(fake_file=args.fake), args.out,
                           interval_s=args.interval)
    signal.signal(signal.SIGINT, logger.request_stop)
    signal.signal(signal.SIGTERM, logger.request_stop)
    logger.run(max_samples=args.samples)
    path = logger.dump()
    print(f"wrote {len(logger.rows)} samples to {path}", flush=True)
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
