"""Publish loop — change-detected inventory writes to the registry.

Parity with profile_gpu.sh:3-13 + cmd/client/client.go:24-79: scrape every
``interval_s``; publish only when the inventory CHANGED (the shell loop
diffs UUID sets) or the heartbeat is older than ``heartbeat_s`` (ours adds a
liveness key so the scheduler can age out dead agents — the reference's
registry entries live forever). Node identity arrives via the same downward
API env the reference uses (NODE_NAME, client-daemonset.yaml:26-40), node
labels via explicit args (in-cluster they'd come from the Node object)."""
from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from ..registry.inventory import (
    HEARTBEAT_SUFFIX,
    NodeInventory,
    node_key,
    publish_inventory,
)
from .scrape import Scraper

log = logging.getLogger(__name__)


class Publisher:
    def __init__(
        self,
        registry,
        scraper: Optional[Scraper] = None,
        node_name: Optional[str] = None,
        accelerator: str = "",
        topology: str = "",
        worker_id: int = 0,
        interval_s: float = 2.0,
        heartbeat_s: float = 30.0,
        metrics_registry=None,
        clock=None,
    ):
        """``metrics_registry``: optional metrics.exporter.Registry — every
        scrape also updates the node's own Prometheus gauges (the TPU_SERIES
        names metrics/client.py queries), so a cluster WITHOUT a third-party
        exporter still has a live /metrics source per node: agent →
        (registry AND re-exporter) → Prometheus → scheduler's PromClient
        fallback. The reference depends on dcgm-exporter existing for this
        whole leg (prom_metrics.go:63-70)."""
        from ..obs import SYSTEM_CLOCK

        self.registry = registry
        self.scraper = scraper or Scraper()
        # Injected time source (obs.Clock): heartbeat STALENESS is a
        # duration and rides monotonic; published_at stays wall time —
        # it crosses processes (the reshaper compares it to its own wall
        # clock).
        self._clock = clock or SYSTEM_CLOCK
        self.metrics_registry = metrics_registry
        self.node_name = node_name or os.environ.get("NODE_NAME", "")
        if not self.node_name:
            raise ValueError("node name required (arg or NODE_NAME env)")
        self.accelerator = accelerator or os.environ.get("TPU_ACCELERATOR_TYPE", "")
        self.topology = topology or os.environ.get("TPU_TOPOLOGY", "")
        self.worker_id = worker_id
        self.interval_s = interval_s
        self.heartbeat_s = heartbeat_s
        self._last_json: Optional[str] = None
        self._last_publish = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def build_inventory(self) -> NodeInventory:
        chips = self.scraper.scrape()
        util = (
            sum(c.duty_cycle for c in chips) / len(chips) if chips else 0.0
        )
        return NodeInventory(
            node_name=self.node_name,
            accelerator=self.accelerator,
            topology=self.topology,
            chips=chips,
            worker_id=self.worker_id,
            utilization=util,
            published_at=self._clock.wall(),
        )

    def export_metrics(self, inv: NodeInventory) -> None:
        """Refresh the re-exporter gauges from one inventory (see __init__).
        Series names/labels match what metrics/client.py parses back."""
        if self.metrics_registry is None:
            return
        from ..metrics.client import HBM_TOTAL, HBM_USED, MXU_DUTY_CYCLE

        duty = self.metrics_registry.gauge(
            MXU_DUTY_CYCLE, "Per-chip MXU duty cycle, percent")
        used = self.metrics_registry.gauge(
            HBM_USED, "Per-chip HBM bytes in use")
        total = self.metrics_registry.gauge(
            HBM_TOTAL, "Per-chip HBM bytes total")
        for c in inv.chips:
            labels = {"node": inv.node_name, "device_id": str(c.device_id)}
            duty.set(round(100.0 * c.duty_cycle, 3), **labels)
            used.set(float(c.hbm_used_bytes), **labels)
            total.set(float(c.hbm_total_bytes), **labels)

    def publish_once(self, force: bool = False) -> bool:
        """Scrape and publish if changed/stale. Returns True if written."""
        inv = self.build_inventory()
        self.export_metrics(inv)
        # Change detection must ignore the timestamp (else every tick
        # "changes") — compare the payload with published_at zeroed.
        probe = NodeInventory(**{**inv.__dict__, "published_at": 0.0}).to_json()
        # Monotonic staleness: on the old wall-clock math an NTP step
        # backward silenced heartbeats for the step's width (dead-agent
        # aging on the scheduler side would fire), a step forward forced
        # a spurious publish — durations never ride the wall clock.
        stale = (self._clock.monotonic() - self._last_publish
                 >= self.heartbeat_s)
        if not force and not stale and probe == self._last_json:
            return False
        publish_inventory(self.registry, inv)
        self.registry.set(
            node_key(self.node_name) + HEARTBEAT_SUFFIX, str(inv.published_at)
        )
        self._last_json = probe
        self._last_publish = self._clock.monotonic()
        return True

    # -- loop --------------------------------------------------------------
    def start(self) -> "Publisher":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"agent-{self.node_name}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.publish_once()
            except Exception:  # noqa: BLE001 — scrape/registry hiccups retry
                log.exception("agent publish failed for %s", self.node_name)
            self._stop.wait(self.interval_s)


def main() -> None:  # pragma: no cover — exercised via CLI
    from ..config import SchedulerConfig
    from ..metrics.exporter import MetricsServer, Registry
    from ..registry.client import Client

    logging.basicConfig(level=logging.INFO)
    cfg = SchedulerConfig.from_env()
    registry = Client(cfg.registry.host, cfg.registry.port,
                      password=cfg.registry.password)
    metrics_registry = Registry()
    port = int(os.environ.get("TPU_AGENT_METRICS_PORT", "8478") or 0)
    if port > 0:
        try:
            server = MetricsServer(
                metrics_registry, host="0.0.0.0", port=port).start()
            log.info("agent re-exporter serving /metrics on :%d", server.port)
        except OSError as e:
            # hostNetwork means the port is shared with the whole node —
            # a taken port must not take down inventory publishing
            # (observability never breaks the agent's primary job).
            log.warning("re-exporter disabled (port %d): %s", port, e)
    Publisher(registry, metrics_registry=metrics_registry)._run()


if __name__ == "__main__":  # pragma: no cover
    main()
