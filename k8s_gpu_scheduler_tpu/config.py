"""Central configuration — replaces the reference's hardcoded constants.

The reference scatters its endpoints and tuning knobs as literals: Redis
password "1234" and NodePort 32767 (gpu_plugins.go:534,859), recommender port
32700 (:317,344), Prometheus port 30090 (:185,272), GPU-model name substrings
(:478,497), MIG configs (:52), MPS memory splits (:898-903), discovery
substrings "-0"/"dcgm"/"prometheus-0"/"recommender" (:471, utils/utils.go:88).
SURVEY.md §5 ("Config / flag system") calls this out as a weakness; here every
knob lives in one dataclass, overridable from the environment (``TPU_SCHED_*``)
the way the reference's recommender already reads PORT/JOB_DELAY
(recom_server.py:30-52).
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional


def _env(name: str, default, cast=None):
    raw = os.environ.get(name)
    if raw is None:
        return default
    if cast is None:
        cast = type(default) if default is not None else str
    if cast is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return cast(raw)


@dataclass
class RegistryConfig:
    """KV registry (the Redis analogue — NodePort 32767, password "1234" in
    the reference; deploy/redis/redis-config.yaml)."""

    host: str = "127.0.0.1"
    port: int = 32767
    password: Optional[str] = None
    db: int = 0
    # Service-discovery fallback: pod-name substring + namespace, parity with
    # FindNodesIPFromPod("-0", "redis") (utils/utils.go:59-70).
    discovery_substring: str = "-0"
    discovery_namespace: str = "registry"


@dataclass
class MetricsConfig:
    """Prometheus-compatible instant-query endpoint (reference port 30090,
    gpu_plugins.go:185)."""

    url: str = "http://127.0.0.1:30090"
    query_timeout_s: float = 2.0


@dataclass
class RecommenderConfig:
    """Prediction service endpoint (reference NodePort 32700,
    gpu_plugins.go:317)."""

    host: str = "127.0.0.1"
    port: int = 32700
    timeout_s: float = 2.0


@dataclass
class SchedulerConfig:
    scheduler_name: str = "tpu-scheduler"
    # Permit phase: how long a gang pod may wait for its peers before the
    # whole gang is rejected (PodGroup.schedule_timeout_s overrides per-group).
    permit_timeout_s: float = 60.0
    # Unschedulable-pod backoff (kube-scheduler defaults).
    backoff_initial_s: float = 1.0
    backoff_max_s: float = 10.0
    # Score weight for the TPU plugin (reference uses weight 10100 in
    # deploy/scheduler.yaml:8-24 to drown out default plugins).
    tpu_score_weight: float = 1.0
    # Filter/Score fan-out: worker threads per cycle (kube-scheduler's
    # --parallelism, default 16); node counts below parallelize_threshold
    # run serial (thread handoff costs more than it saves on small pools).
    parallelism: int = 16
    parallelize_threshold: int = 32
    # Feasible-node sampling above min_feasible_to_find nodes
    # (kube-scheduler's percentageOfNodesToScore): 0 = adaptive
    # (50 - nodes/125, floor 5), otherwise the literal percentage.
    percentage_of_nodes_to_score: int = 0
    min_feasible_to_find: int = 100
    registry: RegistryConfig = field(default_factory=RegistryConfig)
    metrics: MetricsConfig = field(default_factory=MetricsConfig)
    recommender: RecommenderConfig = field(default_factory=RecommenderConfig)

    @staticmethod
    def from_env() -> "SchedulerConfig":
        cfg = SchedulerConfig()
        cfg.scheduler_name = _env("TPU_SCHED_NAME", cfg.scheduler_name)
        cfg.permit_timeout_s = _env("TPU_SCHED_PERMIT_TIMEOUT", cfg.permit_timeout_s, float)
        cfg.backoff_initial_s = _env("TPU_SCHED_BACKOFF_INITIAL", cfg.backoff_initial_s, float)
        cfg.backoff_max_s = _env("TPU_SCHED_BACKOFF_MAX", cfg.backoff_max_s, float)
        cfg.tpu_score_weight = _env("TPU_SCHED_SCORE_WEIGHT", cfg.tpu_score_weight, float)
        cfg.parallelism = _env("TPU_SCHED_PARALLELISM", cfg.parallelism, int)
        cfg.percentage_of_nodes_to_score = _env(
            "TPU_SCHED_PCT_NODES_TO_SCORE", cfg.percentage_of_nodes_to_score, int)
        cfg.registry.host = _env("TPU_SCHED_REGISTRY_HOST", cfg.registry.host)
        cfg.registry.port = _env("TPU_SCHED_REGISTRY_PORT", cfg.registry.port, int)
        cfg.registry.password = _env("TPU_SCHED_REGISTRY_PASSWORD", cfg.registry.password, str)
        cfg.metrics.url = _env("TPU_SCHED_METRICS_URL", cfg.metrics.url)
        cfg.recommender.host = _env("TPU_SCHED_RECOMMENDER_HOST", cfg.recommender.host)
        cfg.recommender.port = _env("TPU_SCHED_RECOMMENDER_PORT", cfg.recommender.port, int)
        return cfg
