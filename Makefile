# Build/test/install — C24 parity (root Makefile + pkg Makefiles +
# install.sh in the reference). `make all` = build native + test, the
# development loop; image/deploy targets mirror the reference's
# docker-build-then-kubectl-apply flow (install.sh:5-17).
PY ?= python
IMG_TAG ?= 0.1.0

.PHONY: all native lint test e2e bench bench-smoke demo images install uninstall clean

all: native lint test

native:
	$(MAKE) -C native/kvstore
	$(MAKE) -C native/tpuprobe

# graftcheck fast passes (AST lint incl. retry-lint + trace-lint
# [trace-in-jit] + the suppression-policy lint [bare-suppression], the
# lock-order & donated-buffer audit [lock-cycle / use-after-donate /
# torn-snapshot], the determinism lint over the replay/placement planes
# [unseeded-rng / builtin-hash / unordered-iteration /
# wall-clock-decision], Pallas VMEM budgeter — no tracing; the same
# gate tier-1 runs via tests/test_graftcheck_clean.py) plus the GSPMD
# sharding audit (--gspmd: tracing-only walk of the sharded entry
# points against the parallel/sharding.py rules table — no compilation,
# seconds). The full twelve-pass analyzer (jaxpr audit +
# recompile/donation guard + alias audit + gspmd + the symbolic
# HBM-traffic/residency audit against the TRAFFIC_CONTRACTS registry +
# the wire-format schema audit against tests/data/graftcheck/schemas/)
# is `$(PY) -m k8s_gpu_scheduler_tpu.analysis` with no flags.
lint:
	$(PY) -m k8s_gpu_scheduler_tpu.analysis --fast --gspmd

test: native
	$(PY) -m pytest tests/

# All-real smoke: kvstored + tpuprobe agents + gRPC recommender + fakekube
# + scheduler booted together; a gang and an SLO singleton scheduled
# through every real seam at once (tests/test_e2e.py).
e2e: native
	$(PY) -m pytest tests/test_e2e.py -q

bench:
	$(PY) bench.py

# CPU-interpret kernel smokes — the fast iteration loop for the Pallas
# decode kernels (the full-line bench runs them too; these are seconds).
bench-smoke:
	$(PY) bench.py --leg paged_attention --smoke
	$(PY) bench.py --leg prefix_cache --smoke
	$(PY) bench.py --leg speculative --smoke
	$(PY) bench.py --leg chaos --smoke
	$(PY) bench.py --leg obs_overhead --smoke
	$(PY) bench.py --leg fleet --smoke
	$(PY) bench.py --leg fleet_chaos --smoke
	$(PY) bench.py --leg chunked_prefill --smoke
	$(PY) bench.py --leg disagg --smoke
	$(PY) bench.py --leg sharded_decode --smoke
	$(PY) bench.py --leg sharded_weights --smoke
	$(PY) bench.py --leg multiturn --smoke
	$(PY) bench.py --leg kv_tiering --smoke
	$(PY) bench.py --leg decode_attention --smoke

demo: native
	$(PY) -m k8s_gpu_scheduler_tpu.cmd.scheduler --demo 8 --once --metrics-port 0

images:
	docker build -f docker/Dockerfile.scheduler -t tpu-scheduler:$(IMG_TAG) .
	docker build -f docker/Dockerfile.agent -t tpu-agent:$(IMG_TAG) .
	docker build -f docker/Dockerfile.registry -t tpu-registry:$(IMG_TAG) .
	docker build -f docker/Dockerfile.recommender -t tpu-recommender:$(IMG_TAG) .
	docker build -f docker/Dockerfile.workloads -t tpu-workloads:$(IMG_TAG) .

install:
	./install.sh

uninstall:
	kubectl delete -f deploy/workloads/ --ignore-not-found
	kubectl delete -f deploy/scheduler/ --ignore-not-found
	kubectl delete -f deploy/recommender/ --ignore-not-found
	kubectl delete -f deploy/agent/ --ignore-not-found
	kubectl delete -f deploy/registry/ --ignore-not-found

clean:
	$(MAKE) -C native/kvstore clean
	$(MAKE) -C native/tpuprobe clean
