#!/usr/bin/env bash
# Install flow — parity with the reference's install.sh:1-17
# (redis → profiler → scheduler, each: build image, kubectl apply).
# Ours: registry → agent → recommender → scheduler (+ CRD), then workloads
# are applied by hand per BASELINE config.
set -euo pipefail
cd "$(dirname "$0")"

make images

kubectl apply -f deploy/registry/
kubectl apply -f deploy/agent/
# Training matrices from the repo (overrides the seed ConfigMap in the
# manifest so repo data updates flow through the md5-watch retrain).
kubectl apply -f deploy/recommender/
kubectl create configmap recommender-train-data \
  --namespace recommender \
  --from-file=k8s_gpu_scheduler_tpu/recommender/data/ \
  --dry-run=client -o yaml | kubectl apply -f -
kubectl apply -f deploy/scheduler/podgroup-crd.yaml
kubectl apply -f deploy/scheduler/rbac.yaml
kubectl apply -f deploy/scheduler/scheduler.yaml

echo "tpu-scheduler installed. Try: kubectl apply -f deploy/workloads/busybox.yaml"
