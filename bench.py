"""Benchmark: the two BASELINE north stars in one JSON line.

1. **p50 pod-schedule latency under 64-pod churn** (headline metric):
   16 v5e hosts, 64 TPU pods created pending at once, full plugin pipeline
   (TPU Filter/Score/Reserve/PostBind with a live in-memory registry);
   latency read from the scheduler's own tpu_sched_e2e_duration_seconds
   histogram. The reference publishes no numbers (BASELINE.md) — baseline
   is the 100 ms order-of-magnitude kube-scheduler placement budget, so
   vs_baseline = 100ms / p50 (higher is better).
2. **Training throughput / MFU** on whatever accelerator is present (the
   real v5e chip under the driver; CPU fallback elsewhere): flagship Llama
   train step, tokens/s × flops_per_token ÷ peak bf16 FLOPs.

Prints exactly ONE JSON line on stdout.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_P50_MS = 100.0

# Public peak bf16 TFLOP/s per chip by device kind substring.
PEAK_TFLOPS = {"v5 lite": 197.0, "v5p": 459.0, "v4": 275.0, "v6": 918.0}


class MemRegistry:
    """In-memory inventory source for the bench legs (the live kvstored is
    benched separately by its own tests; here the registry must not add
    noise to the scheduler numbers)."""

    def __init__(self):
        self.data = {}

    def get(self, key):
        return self.data.get(key)

    def set(self, key, value):
        self.data[key] = value

    def get_keys(self, pattern="*"):
        return [k for k in self.data if k.startswith(pattern.rstrip("*"))]


def bench_schedule_churn(n_nodes=16, n_pods=64, rest=False, suffix=None):
    """Pod churn through the full plugin pipeline. ``rest=False`` drives
    the in-memory APIServer (pure framework overhead); ``rest=True`` drives
    the SAME stack through the Kubernetes REST adapter against a fake HTTP
    apiserver running in a SEPARATE PROCESS (a real apiserver is its own
    process; in-process it shares the GIL and the bench charges the
    scheduler for the server's CPU) — every list/watch/bind is a real HTTP
    round trip, the number comparable to a kube-scheduler p50 that includes
    the apiserver."""
    from k8s_gpu_scheduler_tpu.api.objects import (
        ConfigMap, ConfigMapRef, Container, LABEL_TPU_ACCELERATOR,
        LABEL_TPU_TOPOLOGY, Node, NodeStatus, ObjectMeta, Pod, PodSpec,
        ResourceRequirements, TPU_RESOURCE,
    )
    from k8s_gpu_scheduler_tpu.cluster import APIServer
    from k8s_gpu_scheduler_tpu.config import SchedulerConfig
    from k8s_gpu_scheduler_tpu.plugins import TPUPlugin
    from k8s_gpu_scheduler_tpu.registry.inventory import NodeInventory, node_key
    from k8s_gpu_scheduler_tpu.sched import Profile, Scheduler

    fake_proc = None
    if rest:
        import subprocess

        from k8s_gpu_scheduler_tpu.cluster.kubeapi import KubeAPIServer

        fake_proc = subprocess.Popen(
            [sys.executable, "-m", "tests.fakekube", "--nodes", str(n_nodes)],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE, text=True,
        )
        port_line = fake_proc.stdout.readline().strip()
        assert port_line.startswith("PORT "), port_line
        server = KubeAPIServer(base_url=f"http://127.0.0.1:{port_line.split()[1]}")
    else:
        server = APIServer()
    reg = MemRegistry()
    for i in range(n_nodes):
        name = f"v5e-{i}"
        if not rest:
            server.create(Node(
                metadata=ObjectMeta(name=name, labels={
                    LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
                    LABEL_TPU_TOPOLOGY: "2x4",
                }),
                status=NodeStatus(capacity={TPU_RESOURCE: 8},
                                  allocatable={TPU_RESOURCE: 8}),
            ))
        reg.data[node_key(name)] = NodeInventory(
            node_name=name, utilization=(i % 10) / 10.0
        ).to_json()

    sched = Scheduler(
        server, profile=Profile(),
        config=SchedulerConfig(backoff_initial_s=0.05, backoff_max_s=0.5),
    )
    tpu = TPUPlugin(sched.handle, registry=reg)
    sched.profile = Profile(
        pre_filter=[tpu], filter=[tpu], score=[tpu], reserve=[tpu],
        post_bind=[tpu],
    )
    for i in range(n_pods):
        server.create(ConfigMap(metadata=ObjectMeta(name=f"cm-{i}"), data={}))
        server.create(Pod(
            metadata=ObjectMeta(name=f"churn-{i}"),
            spec=PodSpec(containers=[Container(
                env_from=[ConfigMapRef(f"cm-{i}")],
                resources=ResourceRequirements(requests={TPU_RESOURCE: 2}),
            )]),
        ))

    t0 = time.perf_counter()
    sched.start()
    try:
        hist = sched.metrics.histogram("tpu_sched_e2e_duration_seconds")
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            # Completion check via the scheduler's own bind histogram — a
            # REST LIST here would re-parse every pod each poll, hammering
            # the measured system with the bench's own observer traffic.
            bound = hist.count
            if bound == n_pods:
                break
            time.sleep(0.01)
        wall = time.perf_counter() - t0
        p50 = hist.quantile(0.5) or 0.0
        p99 = hist.quantile(0.99) or 0.0
        assert bound == n_pods, f"only {bound}/{n_pods} bound"
        if suffix is None:
            suffix = "_rest" if rest else ""
        return {
            f"p50{suffix}_ms": round(p50 * 1000, 3),
            f"p99{suffix}_ms": round(p99 * 1000, 3),
            f"pods_per_s{suffix}": round(n_pods / wall, 1),
        }
    finally:
        sched.stop()
        if fake_proc is not None:
            fake_proc.terminate()
            fake_proc.wait(timeout=5)


def bench_mixed(n_nodes=1024, n_single=560, n_gangs=30, rate=150.0):
    """Adversarial scale leg (VERDICT r4 #5): 1024 nodes over REST under a
    MIXED Poisson workload — 560 singletons of varied chip counts, 30
    four-member gangs (slice groups of 4 hosts), a 2-node hot zone
    saturated by low-priority fillers that higher-priority preemptors then
    evict, and one node mid-reshape the whole time. At drain, assert chip
    accounting is ZERO-SUM (every node's bound chips <= capacity, the
    scheduler's own cache agrees with the API state, the fillers are gone)
    and report the scheduler's p50/p99 under that load. The homogeneous
    churn legs above can't surface cross-workload pathologies (the
    reference's O(pods x uuids) hot-loop RPCs only showed under mixed
    load, SURVEY.md §3.2)."""
    import subprocess

    import numpy as np

    from k8s_gpu_scheduler_tpu.api.objects import (
        ANN_RESHAPE_STATE, ConfigMap, ConfigMapRef, Container, ObjectMeta,
        Pod, PodGroup, PodSpec, ResourceRequirements, TPU_RESOURCE,
    )
    from k8s_gpu_scheduler_tpu.cluster.kubeapi import KubeAPIServer
    from k8s_gpu_scheduler_tpu.config import SchedulerConfig
    from k8s_gpu_scheduler_tpu.plugins import (
        GangPlugin, PreemptionPlugin, TPUPlugin,
    )
    from k8s_gpu_scheduler_tpu.registry.inventory import (
        NodeInventory, node_key,
    )
    from k8s_gpu_scheduler_tpu.sched import Profile, Scheduler

    fake_proc = subprocess.Popen(
        [sys.executable, "-m", "tests.fakekube", "--nodes", str(n_nodes),
         "--slice-size", "4", "--hot-nodes", "2"],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        stdout=subprocess.PIPE, text=True,
    )
    try:
        port_line = fake_proc.stdout.readline().strip()
        assert port_line.startswith("PORT "), port_line
        server = KubeAPIServer(
            base_url=f"http://127.0.0.1:{port_line.split()[1]}")
        reg = MemRegistry()
        for i in range(n_nodes):
            reg.data[node_key(f"v5e-{i}")] = NodeInventory(
                node_name=f"v5e-{i}", utilization=(i % 10) / 10.0).to_json()

        # A reshape in flight: this node must be skipped by every Filter
        # for the entire run (the annotation is never cleared).
        def mark(n):
            n.metadata.annotations[ANN_RESHAPE_STATE] = "applying"

        server.mutate("Node", "v5e-37", "default", mark)

        sched = Scheduler(
            server, profile=Profile(),
            # 10% node sampling: the operational knob kube operators turn
            # at this fleet size (percentageOfNodesToScore) — the adaptive
            # default still scores ~42% of 1024 nodes per pod, and the
            # p99 budget is spent walking nodes that can't win anyway.
            config=SchedulerConfig(backoff_initial_s=0.05, backoff_max_s=0.5,
                                   percentage_of_nodes_to_score=10),
        )
        tpu = TPUPlugin(sched.handle, registry=reg)
        gang = GangPlugin(sched.handle)
        profile = Profile(
            pre_filter=[tpu, gang], filter=[tpu, gang], score=[tpu, gang],
            reserve=[tpu, gang], permit=[gang], post_bind=[tpu, gang],
        )
        profile.post_filter.append(PreemptionPlugin(
            sched.handle, filter_plugins=[tpu, gang], tpu=tpu))
        sched.profile = profile

        def submit(name, chips, selector=None, priority=None, group=None,
                   owner=None):
            server.create(ConfigMap(metadata=ObjectMeta(name=f"cm-{name}"),
                                    data={}))
            ann = {"tpu.sched/priority": str(priority)} if priority else {}
            labels = {"tpu.sched/pod-group": group} if group else {}
            server.create(Pod(
                metadata=ObjectMeta(
                    name=name, labels=labels, annotations=ann,
                    # Victims must have a controller owner (preemption.py
                    # never evicts bare pods — they'd be gone forever).
                    owner_references=[owner] if owner else []),
                spec=PodSpec(
                    node_selector=selector or {},
                    containers=[Container(
                        env_from=[ConfigMapRef(f"cm-{name}")],
                        resources=ResourceRequirements(
                            requests={TPU_RESOURCE: chips}),
                    )],
                ),
            ))

        hist = sched.metrics.histogram("tpu_sched_e2e_duration_seconds")
        sched.start()

        # Phase A: saturate the hot zone BEFORE the storm, so the
        # preemptors later have no free hot capacity.
        for i in range(2):
            submit(f"filler-{i}", 8, selector={"zone": "hot"},
                   owner="StatefulSet/fillers")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and hist.count < 2:
            time.sleep(0.02)
        assert hist.count == 2, f"fillers not placed: {hist.count}"

        # Phase B: the Poisson storm — singletons + gangs interleaved.
        rng = np.random.default_rng(0)
        chip_mix = [1, 2, 4, 8]
        events = [("single", i) for i in range(n_single)]
        gang_slots = sorted(rng.choice(len(events), n_gangs, replace=False),
                            reverse=True)
        for j, pos in enumerate(gang_slots):
            events.insert(pos, ("gang", j))
        t0 = time.perf_counter()
        arrival = 0.0
        for kind, idx in events:
            arrival += rng.exponential(1.0 / rate)
            lag = arrival - (time.perf_counter() - t0)
            if lag > 0:
                time.sleep(lag)
            if kind == "single":
                submit(f"job-{idx}", chip_mix[idx % 4])
            else:
                server.create(PodGroup(
                    metadata=ObjectMeta(name=f"gang-{idx}"), min_member=4,
                    topology="", schedule_timeout_s=60.0))
                for m in range(4):
                    submit(f"gang-{idx}-{m}", 8, group=f"gang-{idx}")

        # Phase C: the preemptors — higher priority, hot zone only.
        for i in range(2):
            submit(f"preemptor-{i}", 8, selector={"zone": "hot"},
                   priority=100)

        total_binds = 2 + n_single + 4 * n_gangs + 2
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline and hist.count < total_binds:
            time.sleep(0.05)
        wall = time.perf_counter() - t0
        bound = hist.count
        sched.stop()

        # ---- zero-sum accounting at drain ------------------------------
        pods = server.list("Pod")
        by_node = {}
        for p in pods:
            if p.spec.node_name:
                by_node[p.spec.node_name] = (
                    by_node.get(p.spec.node_name, 0) + p.spec.tpu_chips())
        overcommit = [n for n, c in by_node.items() if c > 8]
        unbound = [p.metadata.name for p in pods if not p.spec.node_name]
        fillers_left = [p.metadata.name for p in pods
                        if p.metadata.name.startswith("filler-")]
        reshaping_used = by_node.get("v5e-37", 0)
        cache_drift = []
        for name, info in sched.cache.snapshot().items():
            want = by_node.get(name, 0)
            have = sum(p.spec.tpu_chips() for p in info.pods)
            if want != have:
                cache_drift.append((name, want, have))
        zero_sum = (not overcommit and not unbound and not fillers_left
                    and reshaping_used == 0 and not cache_drift)
        # Two latency views, mirroring kube-scheduler's metric split:
        # e2e (cycle start -> bind) INCLUDES gang Permit quorum wait — a
        # 4-member gang's first member cannot bind before its peers'
        # cycles have run, so its e2e measures workload shape. The cycle
        # histogram is the per-attempt SCHEDULER work (Filter->Permit),
        # the number the <50 ms bound is about.
        cyc = sched.metrics.histogram("tpu_sched_scheduling_cycle_seconds")
        out = {
            "mixed1024_p50_ms": round((hist.quantile(0.5) or 0) * 1000, 3),
            "mixed1024_p99_ms": round((hist.quantile(0.99) or 0) * 1000, 3),
            "mixed1024_cycle_p50_ms": round(
                (cyc.quantile(0.5) or 0) * 1000, 3),
            "mixed1024_cycle_p99_ms": round(
                (cyc.quantile(0.99) or 0) * 1000, 3),
            "mixed1024_binds": bound,
            "mixed1024_expected_binds": total_binds,
            "mixed1024_pods_per_s": round(bound / wall, 1),
            "mixed1024_preempted": 2 - len(fillers_left),
            "mixed1024_zero_sum": zero_sum,
        }
        # Per-class latency split (VERDICT weak: one distribution for two
        # populations — the aggregate p99 is dominated by gang Permit
        # quorum wait, hiding the kube-comparable singleton tail). The
        # scheduler classifies at bind time (sched.scheduler.pod_class),
        # so the three populations are disjoint and complete.
        for cls in ("single", "gang", "preempting"):
            h = sched.metrics.histogram(
                f"tpu_sched_e2e_duration_seconds_class_{cls}")
            out[f"mixed1024_{cls}_p50_ms"] = round(
                (h.quantile(0.5) or 0) * 1000, 3)
            out[f"mixed1024_{cls}_p99_ms"] = round(
                (h.quantile(0.99) or 0) * 1000, 3)
            out[f"mixed1024_{cls}_binds"] = h.count
        # The singleton tail is the number the 100 ms kube placement
        # budget is about. Reported as a verdict field, NOT asserted
        # in-process: a hard assert here would kill the run before the
        # JSON contract line exists, losing every other metric and
        # reducing the CI gate to a JSON-decode crash. The CI
        # bench-contract job is the single enforcement point.
        out["mixed1024_single_p99_ok"] = bool(
            0 < out["mixed1024_single_p99_ms"] <= BASELINE_P50_MS)
        return out
    finally:
        fake_proc.terminate()
        fake_proc.wait(timeout=5)


def _mfu_one(cfg, B, T, steps):
    """One train-MFU measurement: compile, warm, N steps, ONE host sync at
    the end. float() (unlike block_until_ready, which the axon tunnel
    resolves early) cannot return until the value exists, and the value of
    step N's loss data-depends on steps 1..N-1 through the donated params —
    so this bounds all device work. Syncing every step (round-2 bench)
    charged the ~96 ms tunnel round-trip latency to every step and
    under-read throughput ~2x."""
    import jax
    import jax.numpy as jnp
    import optax

    from k8s_gpu_scheduler_tpu.models import init_params, make_train_step

    dev = jax.devices()[0]
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}
    opt = optax.adamw(1e-4)
    state = opt.init(params)
    step = make_train_step(cfg, None, opt)

    params, state, loss = step(params, state, batch)  # compile + warmup
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, state, loss = step(params, state, batch)
    float(loss)
    dt = (time.perf_counter() - t0) / steps

    tokens_per_s = B * T / dt
    achieved = tokens_per_s * cfg.flops_per_token(T)
    peak = None
    kind = getattr(dev, "device_kind", "") or ""
    for sub, tf in PEAK_TFLOPS.items():
        if sub in kind.lower():
            peak = tf * 1e12
            break
    mfu = round(100.0 * achieved / peak, 2) if peak else None
    return kind or dev.platform, dt, tokens_per_s, mfu


def bench_train_mfu():
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if on_tpu:
        from k8s_gpu_scheduler_tpu.models import LlamaConfig

        # Llama-8B's width (d_model 4096, GQA 2:1) at 2 layers — the widest
        # shape the remote-compile budget allows. Width is what MFU rewards:
        # the r3 d1024×6 shape read 44.6%, this one ~82% on the same chip
        # (each [8192,4096]×[4096,16384] matmul runs the MXU near peak;
        # narrow layers leave it draining between ops). B=12: measured
        # 81.8% MFU vs 79% at B=8 (B=16 exceeds the remote-compile budget).
        wide = LlamaConfig(
            vocab=32000, d_model=4096, n_layers=2, n_heads=32, n_kv_heads=16,
            d_ff=16384, max_seq=1024, remat=False, attn_impl="flash",
        )
        kind, dt, tok_s, mfu = _mfu_one(wide, B=12, T=1024, steps=20)
        out = {
            "device": kind,
            "step_ms": round(dt * 1000, 1),
            "tokens_per_s": round(tok_s, 0),
            "mfu_pct": mfu,
        }
        # REALISTIC DEPTH (VERDICT r4 #6): ~1.2B params (d2048 x 16 layers)
        # with full adamw state — shows the wide-2-layer number is not a
        # depth artifact. remat on: bf16 params+moments ~7 GB, and the
        # un-rematerialized backward's per-layer stashes don't fit next to
        # them at B=8.
        deep = LlamaConfig(
            vocab=32000, d_model=2048, n_layers=16, n_heads=16, n_kv_heads=16,
            d_ff=8192, max_seq=1024, remat=True, attn_impl="flash",
        )
        try:
            _, dt_d, tok_d, mfu_d = _mfu_one(deep, B=8, T=1024, steps=10)
            out.update({
                "step_deep_ms": round(dt_d * 1000, 1),
                "tokens_per_s_deep": round(tok_d, 0),
                "mfu_deep_pct": mfu_d,
                # Model-FLOPs accounting (flops_per_token) excludes the
                # remat recompute, and the deep model does not compile
                # without remat (remote-compile memory budget — measured:
                # B=4 remat=False fails, B=8/12 remat=True run). Full
                # remat re-runs the forward once inside the backward:
                # hardware FLOPs = model FLOPs x (fwd+bwd+fwd)/(fwd+bwd)
                # = 4/3 exactly (attention included — its fwd share is
                # the same 1/3). This line is the profile for the
                # model-MFU gap: 54.6% model = ~73% of the MXU busy.
                "mfu_deep_hw_pct": (round(mfu_d * 4 / 3, 2)
                                    if mfu_d is not None else None),
            })
        except Exception as e:  # noqa: BLE001 — deep leg must not kill wide
            out["mfu_deep_error"] = str(e)[:200]
        return out
    from k8s_gpu_scheduler_tpu.models import LlamaConfig

    cfg = LlamaConfig(
        vocab=1024, d_model=128, n_layers=2, n_heads=8, n_kv_heads=8,
        d_ff=256, max_seq=256, remat=False,
    )
    kind, dt, tok_s, mfu = _mfu_one(cfg, B=2, T=128, steps=2)
    return {
        "device": kind,
        "step_ms": round(dt * 1000, 1),
        "tokens_per_s": round(tok_s, 0),
        "mfu_pct": mfu,
    }


def _pctl(vals, q):
    """Nearest-rank percentile of a list (no numpy needed at call sites)."""
    vals = sorted(vals)
    if not vals:
        return 0.0
    idx = min(len(vals) - 1, max(0, int(round(q * (len(vals) - 1)))))
    return vals[idx]


def _latency_stats(metrics, prefix=""):
    """Fold ContinuousBatcher.pop_request_metrics() records into the
    p50/p99 fields the SLO loop verifies (VERDICT r4 #2: an SLO you never
    measure cannot be verified)."""
    ttft = [m["ttft_s"] * 1000 for m in metrics.values()]
    lat = [m["latency_s"] * 1000 for m in metrics.values()]
    return {
        f"{prefix}ttft_p50_ms": round(_pctl(ttft, 0.50), 1),
        f"{prefix}ttft_p99_ms": round(_pctl(ttft, 0.99), 1),
        f"{prefix}lat_p50_ms": round(_pctl(lat, 0.50), 1),
        f"{prefix}lat_p99_ms": round(_pctl(lat, 0.99), 1),
    }


def bench_serving():
    """BASELINE config 5's serving side: continuous-batching QPS on the
    real chip (skipped on CPU — the interpreted decode would dominate the
    line with noise). Two legs on the small model: a closed 32-request
    batch (engine capacity) and an OPEN-LOOP Poisson-arrival run at a
    quarter of that capacity (see the rate comment at the call site) with
    per-request TTFT/latency percentiles — continuous
    batching's value is admission under load, which a closed batch never
    exercises (VERDICT r4 weak #2)."""
    import numpy as np

    import jax

    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

    if jax.devices()[0].platform == "cpu":
        return {}
    cfg = LlamaConfig(
        vocab=32000, d_model=1024, n_layers=4, n_heads=16, n_kv_heads=16,
        d_ff=4096, max_seq=1024, remat=False,
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    # chunk=64: one dispatch + one readback per 8x64 decoded tokens — the
    # tunnel round trip dominates smaller chunks (measured 2.5x over
    # chunk=16 at identical kernels).
    eng = ContinuousBatcher(params, cfg, n_slots=8, max_len=512, chunk=64,
                            prefill_bucket=128)
    eng.submit(rng.integers(0, cfg.vocab, 64), max_new=65)  # compile both
    eng.run()
    eng.pop_request_metrics()
    n_req, max_new = 32, 64
    t0 = time.perf_counter()
    for _ in range(n_req):
        eng.submit(rng.integers(0, cfg.vocab, 64), max_new=max_new)
    eng.run()
    dt = time.perf_counter() - t0
    eng.pop_request_metrics()
    out = {
        "serve_qps": round(n_req / dt, 2),
        "serve_decode_tok_s": round(n_req * max_new / dt, 0),
    }
    # Open-loop capacity is readback-bound (~n_slots per step, one step per
    # tunnel round trip), well below the closed-batch number — offer at a
    # quarter of closed capacity so the queue is stable and the percentiles
    # describe steady state, not an unbounded ramp.
    out.update(_bench_serving_poisson(eng, cfg, rng, rate=out["serve_qps"] / 4))
    out.update(_bench_serving_int8())
    out.update(_bench_serving_longctx())
    out.update(_bench_serving_8b_full())
    try:
        # Shared-prefix reuse leg: hit rate, prefill tokens skipped and
        # the cache-on/off TTFT before/after on the same workload.
        out.update(bench_prefix_cache()["extra"])
    except Exception as e:  # noqa: BLE001 — reuse leg must not kill the line
        out["prefix_cache_error"] = str(e)[:200]
    return out


def _bench_serving_poisson(eng, cfg, rng, rate: float, n_req: int = 48,
                           prompt: int = 64, max_new: int = 64):
    """Open-loop leg: submissions follow a Poisson process at ``rate``
    req/s; the engine is driven by step() (per-step flush — tokens count
    as delivered only when the host can see them, so the percentiles pay
    the real per-chunk readback the closed batch's single drain hides)."""
    import numpy as np

    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_req))
    done = {}
    t0 = time.perf_counter()
    submitted = 0
    while len(done) < n_req:
        now = time.perf_counter() - t0
        while submitted < n_req and arrivals[submitted] <= now:
            eng.submit(rng.integers(0, cfg.vocab, prompt), max_new=max_new)
            submitted += 1
        if eng.pending:
            done.update(eng.step())
        elif submitted < n_req:
            time.sleep(min(0.005, arrivals[submitted] - now))
    wall = time.perf_counter() - t0
    stats = _latency_stats(eng.pop_request_metrics(), prefix="serve_poisson_")
    stats["serve_poisson_offered_qps"] = round(rate, 2)
    stats["serve_poisson_qps"] = round(n_req / wall, 2)
    return stats


def _wave_tok_s(eng, rng, vocab, n_req=8, max_new=256, prompt=64, waves=3):
    """Best-of-N closed decode waves on a warmed engine — 256-token decodes
    so chunks dispatch back-to-back and the one tunnel round trip per drain
    amortizes; the number reflects device decode bandwidth."""
    best = 0.0
    for _ in range(waves):
        t0 = time.perf_counter()
        for _ in range(n_req):
            eng.submit(rng.integers(0, vocab, prompt), max_new=max_new)
        eng.run()
        best = max(best, n_req * max_new / (time.perf_counter() - t0))
    eng.pop_request_metrics()
    return best


def _bench_serving_int8():
    """Weight precision x KV-cache precision at Llama-8B WIDTH, 2 layers
    (depth-truncated — the full-depth number is _bench_serving_8b_full's):
    decode here is HBM-bound on WEIGHT reads (~0.9 GB int8 vs ~0.13 GB
    cache per step at these shapes), so int8 weights show their gain and
    the int8 KV cache shows only its small share — the cache-bound
    complement is _bench_serving_longctx."""
    import numpy as np

    import jax

    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
    from k8s_gpu_scheduler_tpu.ops import quantize_llama_params

    cfg = LlamaConfig(
        vocab=32000, d_model=4096, n_layers=2, n_heads=32, n_kv_heads=16,
        d_ff=16384, max_seq=1024, remat=False,
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    qparams = quantize_llama_params(params)
    out = {}
    for label, p, kvd in (("bf16", params, None),
                          ("int8", qparams, None),
                          ("int8kv", qparams, "int8")):
        rng = np.random.default_rng(0)
        eng = ContinuousBatcher(p, cfg, n_slots=8, max_len=512, chunk=64,
                                prefill_bucket=128, kv_dtype=kvd)
        eng.submit(rng.integers(0, cfg.vocab, 64), max_new=65)
        eng.run()                                    # compile both programs
        eng.pop_request_metrics()
        out[f"serve_8b_tok_s_{label}"] = round(
            _wave_tok_s(eng, rng, cfg.vocab), 0)
    return out


def _bench_serving_longctx():
    """Cache-bound decode: small weights (~70 MB bf16), 8 slots x 8192-row
    cache — the dense decode attention reads the whole allocated cache
    every token (~2.1 GB bf16 vs 0.14 GB weights), the long-context serving
    regime where an int8 KV cache approaches 2x. Both variants run int8
    weights so the delta isolates the cache.

    Round-5 profile: the dense masked attention's measured gain was
    1.3-1.4x, not the 2x byte ratio — the per-token step materialized f32
    score/prob planes and re-read the repeated GQA cache copy, none of
    which int8 shrinks. The identified fix was a fused Pallas
    decode-attention kernel; this round ships it
    (ops/decode_attention.py), so the leg now runs each cache dtype
    through BOTH decode paths (`*_fused` rows = LlamaConfig.decode_attn
    "fused"), and `bench_decode_attention` isolates the kernel itself."""
    import dataclasses

    import numpy as np

    import jax

    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
    from k8s_gpu_scheduler_tpu.ops import quantize_llama_params

    cfg = LlamaConfig(
        vocab=32000, d_model=1024, n_layers=4, n_heads=16, n_kv_heads=16,
        d_ff=4096, max_seq=8192, remat=False,
    )
    qparams = quantize_llama_params(init_params(cfg, jax.random.PRNGKey(0)))
    out = {}
    for label, kvd, impl, layout in (
            ("bf16kv", None, "dense", "contiguous"),
            ("int8kv", "int8", "dense", "contiguous"),
            ("bf16kv_fused", None, "fused", "contiguous"),
            ("int8kv_fused", "int8", "fused", "contiguous"),
            # Paged rows: the same fused kernel family through the block
            # table — the long-context admission/fragmentation fix must
            # not cost decode bandwidth.
            ("bf16kv_paged", None, "fused", "paged"),
            ("int8kv_paged", "int8", "fused", "paged")):
        rng = np.random.default_rng(0)
        eng = ContinuousBatcher(
            qparams, dataclasses.replace(cfg, decode_attn=impl), n_slots=8,
            max_len=8192, chunk=64, prefill_bucket=128, kv_dtype=kvd,
            kv_layout=layout)
        eng.submit(rng.integers(0, cfg.vocab, 64), max_new=65)
        eng.run()
        eng.pop_request_metrics()
        out[f"serve_longctx_tok_s_{label}"] = round(
            _wave_tok_s(eng, rng, cfg.vocab, waves=2), 0)
        if layout == "paged":
            out[f"serve_longctx_{label}_page_util"] = round(
                eng.pool_metrics()["pages_watermark"]
                / eng.pool_metrics()["pages_total"], 3)
    try:
        out.update(bench_decode_attention()["extra"])
    except Exception as e:  # noqa: BLE001 — microbench must not kill the leg
        out["decattn_error"] = str(e)[:200]
    try:
        out.update(bench_paged_attention()["extra"])
    except Exception as e:  # noqa: BLE001
        out["pagedattn_error"] = str(e)[:200]
    return out


def bench_decode_attention(smoke=False):
    """Decode-attention microbench — the kernel trajectory line for the
    serving engine's hot path: dense grouped einsum vs the fused Pallas
    flash-decode kernel (ops/decode_attention.py), bf16 cache vs int8-KV
    ({int8 rows, f32 per-row scale} from serving._kv_quant). Reports
    tokens/s per variant plus the cache bytes a step must move, so the
    dense-vs-fused delta can be read against the bandwidth bound. On CPU
    (or --smoke) the kernel runs in interpret mode at toy shapes — the
    numbers there only prove the leg runs end-to-end; the TPU run under
    the driver is what BENCH_*.json captures."""
    import jax
    import jax.numpy as jnp

    from k8s_gpu_scheduler_tpu.models.serving import _kv_quant
    from k8s_gpu_scheduler_tpu.ops import (
        dense_decode_reference, flash_decode_attention,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if smoke or not on_tpu:
        B, H, Hkv, hd, S, iters = 2, 8, 4, 64, 256, 2
    else:
        # The long-context serving regime (_bench_serving_longctx's shape
        # family, GQA 4:1): the cache read dominates every other byte.
        B, H, Hkv, hd, S, iters = 8, 32, 8, 128, 8192, 30
    fill = S - 1                                     # near-full cache
    kq_, kk_, kv_ = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq_, (B, H, hd), jnp.bfloat16)
    k = jax.random.normal(kk_, (B, S, Hkv, hd), jnp.bfloat16)
    v = jax.random.normal(kv_, (B, S, Hkv, hd), jnp.bfloat16)
    k8, ks = _kv_quant(k)
    v8, vs = _kv_quant(v)
    lengths = jnp.full((B,), fill, jnp.int32)

    legs = {
        "dense_bf16": (jax.jit(
            lambda q, k, v, n: dense_decode_reference(q, k, v, lengths=n)),
            (q, k, v, lengths)),
        "fused_bf16": (jax.jit(
            lambda q, k, v, n: flash_decode_attention(q, k, v, n)),
            (q, k, v, lengths)),
        "dense_int8kv": (jax.jit(
            lambda q, k, v, n, s1, s2: dense_decode_reference(
                q, k, v, lengths=n, k_scale=s1, v_scale=s2)),
            (q, k8, v8, lengths, ks, vs)),
        "fused_int8kv": (jax.jit(
            lambda q, k, v, n, s1, s2: flash_decode_attention(
                q, k, v, n, k_scale=s1, v_scale=s2)),
            (q, k8, v8, lengths, ks, vs)),
    }
    # K+V rows a dense step reads (the irreducible decode traffic; the
    # fused kernel's length mask cuts it to fill/S of this).
    bytes_bf16 = 2 * B * S * Hkv * hd * 2
    bytes_int8 = 2 * B * S * Hkv * (hd * 1 + 4)
    extra = {
        "decattn_shape": f"B{B} H{H} Hkv{Hkv} hd{hd} S{S} fill{fill}",
        "decattn_interpret": not on_tpu,
        "decattn_bytes_per_step_bf16": bytes_bf16,
        "decattn_bytes_per_step_int8kv": bytes_int8,
    }
    for name, (fn, args) in legs.items():
        out = fn(*args)
        jax.block_until_ready(out)                   # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        extra[f"decattn_{name}_tok_s"] = round(B / dt, 1)
        nbytes = bytes_int8 if "int8" in name else bytes_bf16
        extra[f"decattn_{name}_gb_s"] = round(nbytes / dt / 1e9, 1)
    for kvd in ("bf16", "int8kv"):
        dense = extra[f"decattn_dense_{kvd}_tok_s"]
        fused = extra[f"decattn_fused_{kvd}_tok_s"]
        extra[f"decattn_speedup_{kvd}"] = round(fused / dense, 2) \
            if dense else None
    return {
        "metric": "decode_attention_microbench",
        "value": extra["decattn_fused_int8kv_tok_s"],
        "unit": "tok/s",
        "extra": extra,
    }


def bench_paged_attention(smoke=False):
    """Paged-KV microbench — the kernel trajectory line for the paged
    cache: the table-indirected Pallas kernel (ops/decode_attention.
    paged_decode_attention, block tables as a scalar-prefetch operand)
    against the contiguous fused kernel and both dense formulations, bf16
    and int8-KV, on the SAME logical cache (the paged pool is the
    contiguous cache scattered through a random page permutation — the
    worst case for any accidental locality assumption). Reports tok/s per
    variant, the cache bytes a step must move, and — from a small paged
    ContinuousBatcher wave — the page allocator's utilization metrics
    (pages are worst-case reservations, so utilization < 1 measures the
    reservation slack eos/short decodes leave). On CPU (or --smoke) the
    kernels run interpreted at toy shapes; the TPU run under the driver is
    what BENCH_*.json captures."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from k8s_gpu_scheduler_tpu.models.serving import _kv_quant
    from k8s_gpu_scheduler_tpu.ops import (
        dense_decode_reference, flash_decode_attention, gather_paged_kv,
        paged_decode_attention,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if smoke or not on_tpu:
        B, H, Hkv, hd, S, ps, iters = 2, 8, 4, 64, 256, 64, 2
    else:
        # The long-context serving regime (GQA 4:1, 8192-row caches) at
        # the serving default page size.
        B, H, Hkv, hd, S, ps, iters = 8, 32, 8, 128, 8192, 64, 30
    fill = S - 1                                     # near-full cache
    nb = S // ps
    kq_, kk_, kv_ = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq_, (B, H, hd), jnp.bfloat16)
    k = jax.random.normal(kk_, (B, S, Hkv, hd), jnp.bfloat16)
    v = jax.random.normal(kv_, (B, S, Hkv, hd), jnp.bfloat16)
    k8, ks = _kv_quant(k)
    v8, vs = _kv_quant(v)
    lengths = jnp.full((B,), fill, jnp.int32)
    # Paged twin: the same logical rows scattered through a permutation.
    rng = np.random.default_rng(0)
    table = jnp.asarray(
        rng.permutation(np.arange(1, 1 + B * nb)).reshape(B, nb), jnp.int32)

    def pool_of(a):
        pooled = jnp.zeros((1 + B * nb, ps) + a.shape[2:], a.dtype)
        return pooled.at[table].set(a.reshape(B, nb, ps, *a.shape[2:]))

    kp, vp = pool_of(k), pool_of(v)
    kp8, vp8 = pool_of(k8), pool_of(v8)
    kps, vps = pool_of(ks), pool_of(vs)

    legs = {
        "contig_dense_bf16": (jax.jit(
            lambda q, k, v, n: dense_decode_reference(q, k, v, lengths=n)),
            (q, k, v, lengths)),
        "contig_fused_bf16": (jax.jit(
            lambda q, k, v, n: flash_decode_attention(q, k, v, n)),
            (q, k, v, lengths)),
        "paged_dense_bf16": (jax.jit(
            lambda q, k, v, t, n: dense_decode_reference(
                q, gather_paged_kv(k, t), gather_paged_kv(v, t),
                lengths=n)),
            (q, kp, vp, table, lengths)),
        "paged_fused_bf16": (jax.jit(
            lambda q, k, v, t, n: paged_decode_attention(q, k, v, t, n)),
            (q, kp, vp, table, lengths)),
        "contig_dense_int8kv": (jax.jit(
            lambda q, k, v, n, s1, s2: dense_decode_reference(
                q, k, v, lengths=n, k_scale=s1, v_scale=s2)),
            (q, k8, v8, lengths, ks, vs)),
        "contig_fused_int8kv": (jax.jit(
            lambda q, k, v, n, s1, s2: flash_decode_attention(
                q, k, v, n, k_scale=s1, v_scale=s2)),
            (q, k8, v8, lengths, ks, vs)),
        "paged_dense_int8kv": (jax.jit(
            lambda q, k, v, t, n, s1, s2: dense_decode_reference(
                q, gather_paged_kv(k, t), gather_paged_kv(v, t), lengths=n,
                k_scale=gather_paged_kv(s1, t),
                v_scale=gather_paged_kv(s2, t))),
            (q, kp8, vp8, table, lengths, kps, vps)),
        "paged_fused_int8kv": (jax.jit(
            lambda q, k, v, t, n, s1, s2: paged_decode_attention(
                q, k, v, t, n, k_scale=s1, v_scale=s2)),
            (q, kp8, vp8, table, lengths, kps, vps)),
    }
    bytes_bf16 = 2 * B * S * Hkv * hd * 2
    bytes_int8 = 2 * B * S * Hkv * (hd * 1 + 4)
    extra = {
        "pagedattn_shape": f"B{B} H{H} Hkv{Hkv} hd{hd} S{S} ps{ps} "
                           f"fill{fill}",
        "pagedattn_interpret": not on_tpu,
        "pagedattn_bytes_per_step_bf16": bytes_bf16,
        "pagedattn_bytes_per_step_int8kv": bytes_int8,
        "pagedattn_table_bytes": int(B * nb * 4),
    }
    for name, (fn, args) in legs.items():
        out = fn(*args)
        jax.block_until_ready(out)                   # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        extra[f"pagedattn_{name}_tok_s"] = round(B / dt, 1)
        nbytes = bytes_int8 if "int8" in name else bytes_bf16
        extra[f"pagedattn_{name}_gb_s"] = round(nbytes / dt / 1e9, 1)
    for kvd in ("bf16", "int8kv"):
        contig = extra[f"pagedattn_contig_fused_{kvd}_tok_s"]
        paged = extra[f"pagedattn_paged_fused_{kvd}_tok_s"]
        extra[f"pagedattn_paged_vs_contig_{kvd}"] = round(paged / contig, 2) \
            if contig else None
    extra.update(_paged_engine_utilization())
    return {
        "metric": "paged_attention_microbench",
        "value": extra["pagedattn_paged_fused_int8kv_tok_s"],
        "unit": "tok/s",
        "extra": extra,
    }


def _paged_engine_utilization():
    """A small paged-engine wave for the allocator-side numbers: page
    watermark/utilization under a mixed-length burst (host-side allocator
    properties — shape-independent, so the toy model is honest)."""
    import numpy as np

    import jax

    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=64, chunk=4,
                            prefill_bucket=8, kv_dtype="int8",
                            kv_layout="paged", page_size=8)
    rng = np.random.default_rng(0)
    peak = 0.0
    for plen, mn in ((5, 9), (11, 5), (3, 13), (17, 3)):
        eng.submit(rng.integers(0, cfg.vocab, plen), max_new=mn)
    while eng.pending:
        eng.step()
        peak = max(peak, eng.pool_metrics()["page_utilization"])
    m = eng.pool_metrics()
    return {
        "paged_engine_pages_total": m["pages_total"],
        "paged_engine_pages_watermark": m["pages_watermark"],
        "paged_engine_page_allocs": m["page_allocs"],
        "paged_engine_page_utilization_peak": round(peak, 3),
    }


def bench_prefix_cache(smoke=False):
    """Shared-prefix serving leg — the prefix cache's value proposition
    measured end-to-end: N requests over K distinct system prompts (the
    many-users-few-prompts regime the ROADMAP north star implies) through
    a paged ContinuousBatcher with `prefix_cache=True`, step()-driven so
    admission-to-first-token pays the real readback cadence. Reports
    TTFT percentiles cache-on AND cache-off on the identical workload
    (the before/after), prefill tokens skipped, token- and
    request-weighted hit rates, page utilization and evictions. On CPU
    (or --smoke) the model is tiny and fused attention runs interpreted —
    the numbers prove the leg end-to-end; the TPU run under the driver is
    what BENCH_*.json captures."""
    import dataclasses

    import numpy as np

    import jax

    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

    on_tpu = jax.devices()[0].platform == "tpu"
    if smoke or not on_tpu:
        cfg = dataclasses.replace(LlamaConfig.tiny(), decode_attn="fused")
        n_req, n_sys, sys_len, suffix, max_new = 24, 2, 24, 6, 4
        eng_kw = dict(n_slots=4, max_len=64, chunk=4, prefill_bucket=8,
                      page_size=8)
    else:
        # The serving regime of _bench_serving_longctx, shared-prefix
        # edition: few long system prompts, short novel suffixes.
        cfg = LlamaConfig(
            vocab=32000, d_model=1024, n_layers=4, n_heads=16,
            n_kv_heads=16, d_ff=4096, max_seq=2048, remat=False,
            decode_attn="fused")
        n_req, n_sys, sys_len, suffix, max_new = 48, 4, 960, 32, 32
        eng_kw = dict(n_slots=8, max_len=2048, chunk=32,
                      prefill_bucket=128, page_size=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    sys_prompts = [list(rng.integers(0, cfg.vocab, sys_len))
                   for _ in range(n_sys)]
    workload = [sys_prompts[i % n_sys]
                + list(rng.integers(0, cfg.vocab, suffix))
                for i in range(n_req)]

    def drive(prefix_cache: bool):
        eng = ContinuousBatcher(params, cfg, kv_dtype="int8",
                                kv_layout="paged",
                                prefix_cache=prefix_cache, **eng_kw)
        # Warm OUTSIDE the measured window: two waves over the K system
        # prompts — the first misses and (cache on) donates them into the
        # tree, the second hits, so every (tb, hb) prefill rung the
        # measured workload uses is compiled and the cache is in its
        # steady state (K hot system prompts — the workload's premise).
        for _ in range(2):
            for sp in sys_prompts:
                eng.submit(sp + list(rng.integers(0, cfg.vocab, suffix)),
                           max_new=2)
            while eng.pending:
                eng.step()
        eng.pop_request_metrics()
        warm = eng.pool_metrics()
        t0 = time.perf_counter()
        for p in workload:
            eng.submit(p, max_new=max_new)
        while eng.pending:
            eng.step()
        wall = time.perf_counter() - t0
        eng._alloc.assert_consistent()
        return eng, warm, wall, eng.pop_request_metrics()

    eng_on, warm, wall_on, met_on = drive(True)
    eng_off, _, wall_off, met_off = drive(False)
    m = eng_on.pool_metrics()

    def delta_rate(hit_key, total_key):
        num = m[hit_key] - warm[hit_key]
        den = m[total_key] - warm[total_key]
        return round(num / den, 4) if den else 0.0

    extra = {
        "prefix_cache_shape": f"{n_req} reqs x {n_sys} sys prompts "
                              f"(sys {sys_len} + suffix {suffix})",
        "prefix_cache_interpret": not on_tpu,
        # Measured-window deltas: the steady-state numbers, not diluted
        # by the warmup's compulsory misses.
        "prefix_cache_tokens_skipped": m["prefill_tokens_skipped"]
                                       - warm["prefill_tokens_skipped"],
        "prefix_cache_hit_rate": delta_rate("prefix_hit_tokens",
                                            "prefix_lookup_tokens"),
        "prefix_cache_request_hit_rate": delta_rate("prefix_lookup_hits",
                                                    "prefix_lookups"),
        "prefix_cache_cached_pages": m["prefix_cached_pages"],
        "prefix_cache_evictions": m["prefix_evictions"],
        "prefix_cache_page_utilization": round(m["page_utilization"], 4),
        "prefix_cache_tok_s": round(n_req * max_new / wall_on, 1),
        "prefix_cache_off_tok_s": round(n_req * max_new / wall_off, 1),
    }
    extra.update(_latency_stats(met_on, prefix="prefix_cache_"))
    extra.update(_latency_stats(met_off, prefix="prefix_cache_off_"))
    return {
        "metric": "prefix_cache_bench",
        "value": extra["prefix_cache_request_hit_rate"],
        "unit": "hit_rate",
        "extra": extra,
    }


def bench_speculative(smoke=False):
    """Speculative-decoding serving leg — prompt-lookup speculation inside
    the paged ContinuousBatcher measured end-to-end on a REPETITIVE-TEXT
    workload (the regime where bigram lookup hits: code, boilerplate,
    templated documents — emulated by prompts that seed a repeating
    phrase the greedy stream then cycles on). Drives the identical
    workload spec-on (one multi-query verify dispatch per step,
    committing 1..gamma+1 tokens/slot) and spec-off (one chunk of
    single-token dispatches per step) and reports accept rate, committed
    tokens per slot per verify dispatch, both tok/s figures and their
    ratio, the rewound overshoot, and the token-identity bit the CI step
    asserts (speculation must be a pure speedup, never a different
    stream). On CPU (or --smoke) the model is tiny/f32 with the kernel
    interpreted — numbers prove the leg end-to-end; the TPU run under
    the driver is what BENCH_*.json captures."""
    import dataclasses

    import numpy as np

    import jax
    import jax.numpy as jnp

    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

    on_tpu = jax.devices()[0].platform == "tpu"
    if smoke or not on_tpu:
        # f32 on CPU: the identity assert must see no bf16 near-tie noise
        # between the 1-token and (1+gamma)-token program shapes.
        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32,
                                  decode_attn="fused")
        n_req, phrase_len, reps, max_new, gamma = 8, 4, 3, 16, 4
        eng_kw = dict(n_slots=4, max_len=96, chunk=4, prefill_bucket=16,
                      page_size=8)
    else:
        # The long-context serving regime of the other legs, speculative
        # edition: bf16 weights, int8 KV, fused kernels.
        cfg = LlamaConfig(
            vocab=32000, d_model=1024, n_layers=4, n_heads=16,
            n_kv_heads=16, d_ff=4096, max_seq=2048, remat=False,
            decode_attn="fused")
        n_req, phrase_len, reps, max_new, gamma = 32, 16, 8, 64, 4
        eng_kw = dict(n_slots=8, max_len=2048, chunk=8, prefill_bucket=128,
                      page_size=64, kv_dtype="int8")
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    workload = []
    for _ in range(n_req):
        phrase = list(rng.integers(0, cfg.vocab, phrase_len))
        workload.append(phrase * reps)

    def drive(spec: bool, **kw):
        eng = ContinuousBatcher(params, cfg, kv_layout="paged",
                                speculative=spec, gamma=gamma, **eng_kw,
                                **kw)
        # Warm OUTSIDE the measured window: compiles the prefill rung and
        # the verify (or decode-chunk) program.
        eng.submit(workload[0], max_new=2)
        eng.run()
        eng.pop_request_metrics()
        t0 = time.perf_counter()
        ids = [eng.submit(p, max_new=max_new) for p in workload]
        done = eng.run()
        wall = time.perf_counter() - t0
        eng._alloc.assert_consistent()
        return [done[i] for i in ids], wall, eng

    toks_on, wall_on, eng_on = drive(True)
    toks_off, wall_off, _ = drive(False)
    m = eng_on.pool_metrics()
    # Sampled rows: rejection-sampling verify at a temperature well under
    # the logit scale (random-init weights leave logits nearly flat, so
    # the repetitive stream only self-locks — and proposals only accept —
    # once p sharpens; a trained model reaches this regime at ordinary
    # temperatures). Replay determinism doubles as the cheap in-bench
    # distribution check: the sampled stream is a pure function of the
    # seeded PRNG chain, so two identical drives must agree exactly
    # (the full TV-distance equivalence test lives in
    # tests/test_speculative_batcher.py).
    temp = 0.005
    toks_s1, wall_s, eng_s = drive(True, temperature=temp)
    toks_s2, _, _ = drive(True, temperature=temp)
    ms = eng_s.pool_metrics()
    # Adaptive row: the accept-rate EMA sizes per-slot effective windows.
    _, wall_a, eng_a = drive(True, temperature=temp, spec_adaptive=True)
    ma = eng_a.pool_metrics()
    # Draft row: a draft proposer sharing the target weights and sampler
    # is the q == p full-accept ceiling — accept machinery at its limit
    # (a REAL deployment pairs a much smaller draft; this row isolates
    # the verify/accept cost at accept-rate 1).
    from k8s_gpu_scheduler_tpu.models.proposers import DraftModelProposer

    draft = DraftModelProposer(cfg, params, temperature=temp,
                               ctx=min(64, cfg.max_seq))
    _, wall_d, eng_d = drive(True, temperature=temp, proposer=draft)
    md = eng_d.pool_metrics()
    extra = {
        "spec_shape": f"{n_req} reqs x ({phrase_len}-tok phrase x {reps}), "
                      f"max_new {max_new}, gamma {gamma}",
        "spec_interpret": not on_tpu,
        "spec_accept_rate": round(m["spec_accept_rate"], 4),
        "spec_tokens_per_dispatch": round(m["spec_tokens_per_dispatch"], 3),
        "spec_rewound_tokens": m["spec_rewound_tokens_total"],
        "spec_on_tok_s": round(n_req * max_new / wall_on, 1),
        "spec_off_tok_s": round(n_req * max_new / wall_off, 1),
        "spec_speedup": round(wall_off / wall_on, 3) if wall_on else None,
        "spec_token_identity": toks_on == toks_off,
        "spec_sampled_temperature": temp,
        "spec_sampled_accept_rate": round(ms["spec_accept_rate"], 4),
        "spec_sampled_tokens_per_dispatch":
            round(ms["spec_tokens_per_dispatch"], 3),
        "spec_sampled_tok_s": round(n_req * max_new / wall_s, 1),
        "spec_sampled_replay_identity": toks_s1 == toks_s2,
        "spec_adaptive_tokens_per_dispatch":
            round(ma["spec_tokens_per_dispatch"], 3),
        "spec_adaptive_gamma_mean":
            round(ma["spec_gamma_agg"]["mean"], 3),
        "spec_draft_accept_rate": round(md["spec_accept_rate"], 4),
        "spec_draft_tokens_per_dispatch":
            round(md["spec_tokens_per_dispatch"], 3),
    }
    return {
        "metric": "speculative_bench",
        "value": extra["spec_tokens_per_dispatch"],
        "unit": "tok/dispatch",
        "extra": extra,
    }


def bench_analysis(smoke=False):
    """graftcheck latency leg: wall time of the analyzer over the whole
    repo, recorded in BENCH_r*.json so lint latency is a tracked metric —
    a pass that quietly grows from 2 s to 2 minutes is a CI tax nobody
    budgeted. ``--smoke`` (and the headline value either way) times the
    FAST passes (AST lint + lock-order + determinism + VMEM — what
    tier-1 runs every collection); the full twelve-pass wall time
    (jaxpr, recompile, alias, gspmd, symbolic traffic, wirecompat)
    rides in ``extra`` unless smoking, one ``analysis_<pass>_s`` key
    per pass (so ``analysis_determinism_s`` / ``analysis_wirecompat_s``
    flow with the rest)."""
    if not smoke:
        # Mirror the CLI's env (analysis/__main__.py): the traced passes
        # want hermetic CPU and a multi-device mesh for the pipeline entry
        # point. setdefault is a no-op when jax is already initialized
        # (full-line callers run smoke=True, so only the standalone leg
        # reaches here before the first jax import).
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import k8s_gpu_scheduler_tpu
    from k8s_gpu_scheduler_tpu.analysis import (
        run_fast_passes, run_traced_passes,
    )

    pkg = os.path.dirname(os.path.abspath(k8s_gpu_scheduler_tpu.__file__))
    t0 = time.perf_counter()
    fast = run_fast_passes([pkg])
    fast_s = time.perf_counter() - t0
    extra = {
        "analysis_fast_s": round(fast_s, 3),
        "analysis_findings": len(fast.findings),
        **{f"analysis_{k}_s": round(v, 3)
           for k, v in fast.pass_seconds.items()},
    }
    if not smoke:
        t0 = time.perf_counter()
        traced = run_traced_passes([pkg])
        extra["analysis_traced_s"] = round(time.perf_counter() - t0, 3)
        extra["analysis_findings"] += len(traced.findings)
        extra.update({f"analysis_{k}_s": round(v, 3)
                      for k, v in traced.pass_seconds.items()})
    return {
        "metric": "analysis_lint_wall",
        "value": round(fast_s, 3),
        "unit": "s",
        "extra": extra,
    }


def _random_int8_llama_params(cfg, seed: int = 0):
    """Random FULL-DEPTH int8 params built directly on device in quantized
    form ({"q","s"} leaves, ops/quant.py layout): a real 8B never exists in
    bf16 on a 16 GB chip next to its int8 copy, and the bench only needs
    weight BYTES to be honest — values are irrelevant to fixed-budget
    greedy throughput."""
    import jax
    import jax.numpy as jnp

    D, H, Hkv, hd, F, L, V = (cfg.d_model, cfg.n_heads, cfg.n_kv_heads,
                              cfg.head_dim, cfg.d_ff, cfg.n_layers, cfg.vocab)

    def build(key):
        ks = jax.random.split(key, 10)

        def q(k, *shape):
            return {"q": jax.random.randint(k, shape, -127, 128, jnp.int8),
                    "s": jnp.full(shape[:-2] + (1, shape[-1]), 0.01,
                                  jnp.float32)}

        return {
            "embed": (jax.random.normal(ks[0], (V, D), jnp.float32)
                      * 0.02).astype(cfg.dtype),
            "blocks": {
                "attn_norm": jnp.ones((L, D), cfg.dtype),
                "wq": q(ks[1], L, D, H * hd),
                "wk": q(ks[2], L, D, Hkv * hd),
                "wv": q(ks[3], L, D, Hkv * hd),
                "wo": q(ks[4], L, H * hd, D),
                "mlp_norm": jnp.ones((L, D), cfg.dtype),
                "w_gate": q(ks[5], L, D, F),
                "w_up": q(ks[6], L, D, F),
                "w_down": q(ks[7], L, F, D),
            },
            "final_norm": jnp.ones((D,), cfg.dtype),
            "lm_head": q(ks[8], D, V),
        }

    return jax.jit(build)(jax.random.PRNGKey(seed))


def _bench_serving_8b_full():
    """FULL-DEPTH Llama-8B serving (VERDICT r4 #1): 32 layers, d_model
    4096, GQA 4:1, the llama3_8b architecture — ~7.4 GB of int8 weights +
    int8 KV cache, resident on the one 16 GB chip. Reports end-to-end
    decode tok/s AND per-request TTFT/latency percentiles from a step()-
    driven wave (per-step flush: tokens count when the host sees them)."""
    import numpy as np

    from k8s_gpu_scheduler_tpu.models import LlamaConfig
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

    cfg = LlamaConfig(
        vocab=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_ff=14336, max_seq=1024, remat=False,
    )
    params = _random_int8_llama_params(cfg)
    rng = np.random.default_rng(0)
    eng = ContinuousBatcher(params, cfg, n_slots=8, max_len=512, chunk=32,
                            prefill_bucket=128, kv_dtype="int8")
    eng.submit(rng.integers(0, cfg.vocab, 64), max_new=33)   # compile
    eng.run()
    eng.pop_request_metrics()
    n_req, max_new = 8, 128
    # Latency wave: step()-driven, per-chunk flush — TTFT/p99 pay the real
    # readback cadence a client would see.
    for _ in range(n_req):
        eng.submit(rng.integers(0, cfg.vocab, 64), max_new=max_new)
    done = {}
    while eng.pending:
        done.update(eng.step())
    stats = _latency_stats(eng.pop_request_metrics(), prefix="serve_8b_full_")
    # Throughput wave: run()'s deferred readback (one round trip per
    # drain), so tok/s reflects device decode bandwidth, not the tunnel.
    t0 = time.perf_counter()
    for _ in range(n_req):
        eng.submit(rng.integers(0, cfg.vocab, 64), max_new=max_new)
    eng.run()
    wall = time.perf_counter() - t0
    eng.pop_request_metrics()
    stats["serve_8b_full_tok_s"] = round(n_req * max_new / wall, 0)
    return stats


def bench_chaos(smoke=False):
    """Preemption-safe serving leg — the robustness PR's loop measured
    end-to-end: a mixed workload is forced through a preemption at ~50%
    completion (a seeded ``FaultRule`` preempt on the batcher's
    ``serve.step`` hook), drained, snapshotted (models/snapshot.py),
    and restored into a FRESH engine that finishes the run. Reports
    drain ms, snapshot bytes, restore ms, resumed-request count, and
    the ``chaos_token_identity`` bit (resumed streams byte-equal to the
    uninterrupted reference) the CI step asserts; plus the
    bounded-retry proof for the control-plane clients (a dead registry
    costs exactly the attempt budget, inside the deadline, never a
    hang) and the determinism bit (same fault seed → same injection
    log → same streams). On CPU (or --smoke) the model is tiny/f32 —
    numbers prove the loop end-to-end; the TPU run under the driver is
    what BENCH_*.json captures."""
    import dataclasses

    import numpy as np

    import jax
    import jax.numpy as jnp

    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
    from k8s_gpu_scheduler_tpu.models.snapshot import ServingSnapshot
    from k8s_gpu_scheduler_tpu.testing.faults import (
        FaultInjector, FaultRule, Preempted,
    )
    from k8s_gpu_scheduler_tpu.utils.retry import RetryPolicy

    on_tpu = jax.devices()[0].platform == "tpu"
    if smoke or not on_tpu:
        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
        n_req, max_new = 8, 12
        eng_kw = dict(n_slots=4, max_len=96, chunk=4, prefill_bucket=16,
                      kv_layout="paged", page_size=8, prefix_cache=True)
    else:
        cfg = LlamaConfig(
            vocab=32000, d_model=1024, n_layers=4, n_heads=16,
            n_kv_heads=16, d_ff=4096, max_seq=2048, remat=False,
            decode_attn="fused")
        n_req, max_new = 32, 48
        eng_kw = dict(n_slots=8, max_len=2048, chunk=8,
                      prefill_bucket=128, kv_layout="paged", page_size=64,
                      kv_dtype="int8", prefix_cache=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    shared = list(rng.integers(0, cfg.vocab, 2 * eng_kw["page_size"]))
    workload = [shared + list(rng.integers(0, cfg.vocab, 3 + i % 7))
                for i in range(n_req)]

    from k8s_gpu_scheduler_tpu.obs import Tracer, validate_perfetto, \
        write_perfetto

    # One tracer across the preempted AND restored engines: the exported
    # Perfetto file shows the whole preemption story (decode chunks →
    # drain → restore → resumed chunks) on one timeline — the artifact
    # the CI schema-check loads.
    chaos_tracer = Tracer(capacity=1 << 16)

    def engine(injector=None, tracer=None):
        return ContinuousBatcher(params, cfg, fault_injector=injector,
                                 tracer=tracer, **eng_kw)

    # Uninterrupted reference (also counts the steps so the preempt can
    # land at ~50% completion).
    eng = engine()
    ids = [eng.submit(p, max_new=max_new) for p in workload]
    ref, steps = {}, 0
    while eng.pending:
        ref.update(eng.step())
        steps += 1
    ref = [ref[i] for i in ids]

    def chaos_run(tracer=None):
        inj = FaultInjector(seed=42, rules=[
            FaultRule(site="serve.step", kind="preempt",
                      at=[max(2, steps // 2)]),
        ])
        eng = engine(inj, tracer=tracer)
        for p in workload:
            eng.submit(p, max_new=max_new)
        done = {}
        try:
            while eng.pending:
                done.update(eng.step())
            raise RuntimeError("injected preemption never fired")
        except Preempted:
            pass
        snap = eng.drain()
        nbytes = snap.nbytes()
        # The persistence path's codec round trip (orbax itself is
        # exercised in tests/test_snapshot_restore.py; the bench keeps
        # the loop dependency-light).
        snap = ServingSnapshot.from_pytree(snap.to_pytree())
        fresh = engine(tracer=tracer)
        t0 = time.perf_counter()
        resumed = fresh.restore(snap)
        restore_s = time.perf_counter() - t0
        while fresh.pending:
            done.update(fresh.step())
        fresh._alloc.assert_consistent()
        return ([done[i] for i in ids], inj.log, eng, resumed,
                nbytes, restore_s)

    toks, log1, drained_eng, resumed, snap_bytes, restore_s = chaos_run(
        chaos_tracer)
    toks2, log2, *_ = chaos_run()          # determinism: same seed, again

    # Bounded-retry proof, no server needed: a dead registry endpoint
    # costs exactly the attempt budget inside the deadline.
    from k8s_gpu_scheduler_tpu.registry.client import Client, ConnectionLost

    retries = []
    rc = Client(port=1, timeout_s=0.2,
                retry=RetryPolicy(attempts=3, base_s=0.005, max_s=0.02,
                                  jitter=0.5, deadline_s=2.0),
                on_retry=lambda: retries.append(1))
    t0 = time.perf_counter()
    try:
        rc.get("probe")
        rpc_bounded = False                # a dead port must not succeed
    except ConnectionLost:
        rpc_bounded = (time.perf_counter() - t0) < 2.0 \
            and len(retries) == 2
    except Exception:  # noqa: BLE001 — unexpected error type = not bounded proof
        rpc_bounded = False

    extra = {
        "chaos_shape": f"{n_req} reqs (shared {2 * eng_kw['page_size']}-tok "
                       f"prefix), max_new {max_new}, preempt at step "
                       f"{max(2, steps // 2)}/{steps}",
        "chaos_interpret": not on_tpu,
        "chaos_drain_ms": round(
            drained_eng.pool_metrics()["drain_duration_seconds"] * 1e3, 2),
        "chaos_snapshot_bytes": snap_bytes,
        "chaos_restore_ms": round(restore_s * 1e3, 2),
        "chaos_resumed_requests": resumed,
        "chaos_token_identity": toks == ref and toks2 == ref,
        "chaos_deterministic": log1 == log2 and bool(log1),
        "chaos_rpc_retries_bounded": rpc_bounded,
    }
    # Perfetto artifact from the traced chaos run (decode → drain →
    # restore → resumed decode on one timeline) + the schema check the
    # CI step asserts.
    import tempfile

    chaos_spans = chaos_tracer.spans()
    perfetto_path = os.path.join(tempfile.gettempdir(),
                                 "chaos_trace_perfetto.json")
    doc = write_perfetto(chaos_spans, perfetto_path)
    problems = validate_perfetto(doc)
    names = {s.name for s in chaos_spans}
    extra.update({
        "chaos_perfetto_valid": not problems and {
            "decode_chunk", "drain", "restore"} <= names,
        "chaos_perfetto_path": perfetto_path,
        "chaos_perfetto_spans": len(chaos_spans),
    })
    return {
        "metric": "chaos_bench",
        "value": extra["chaos_restore_ms"],
        "unit": "ms",
        "extra": extra,
    }


def bench_obs_overhead(smoke=False):
    """Observability-overhead leg — the off-by-default-cheap CONTRACT of
    the obs/ tracing subsystem, measured: the steady-state paged decode
    workload runs tracing-OFF and tracing-ON (obs.Tracer attached:
    queue/admit/prefill/decode_chunk/reap spans + per-slot lanes + the
    phase-histogram fold per step) and the tok/s delta must stay under
    2% — the bit the CI step asserts. Zero-retrace is re-asserted with
    tracing enabled (spans are host-side only; same jit keys), and the
    streams must be token-identical (tracing observes, never schedules).
    A second, SPECULATIVE traced wave (random prompts — 0-accept full
    rewinds) tops up the phase coverage, and the combined spans export
    to a Perfetto/Chrome-trace JSON that must pass the schema check with
    every lifecycle phase present (admission + prefill + >=3 decode
    chunks + spec verify + rewind + reap). Best-of-N walls per mode: the
    overhead bound is a property of the code, not of CI machine jitter.
    """
    import dataclasses
    import tempfile

    import numpy as np

    import jax
    import jax.numpy as jnp

    from k8s_gpu_scheduler_tpu.analysis.recompile import RecompileGuard
    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
    from k8s_gpu_scheduler_tpu.obs import (
        Tracer, validate_perfetto, write_perfetto,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if smoke or not on_tpu:
        # f32 on CPU: the identity assert must see no bf16 near-tie noise.
        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32,
                                  decode_attn="fused")
        n_req, max_new, repeats = 8, 24, 6
        eng_kw = dict(n_slots=4, max_len=96, chunk=8, prefill_bucket=16,
                      page_size=8)
    else:
        cfg = LlamaConfig(
            vocab=32000, d_model=1024, n_layers=4, n_heads=16,
            n_kv_heads=16, d_ff=4096, max_seq=2048, remat=False,
            decode_attn="fused")
        n_req, max_new, repeats = 32, 64, 5
        eng_kw = dict(n_slots=8, max_len=2048, chunk=8,
                      prefill_bucket=128, page_size=64, kv_dtype="int8")
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    workload = [list(rng.integers(0, cfg.vocab, 5 + i % 9))
                for i in range(n_req)]

    def setup(tracer):
        eng = ContinuousBatcher(params, cfg, kv_layout="paged",
                                tracer=tracer, **eng_kw)
        # Warm >= 2 decode chunks: the committed-vs-numpy block-table jit
        # keys both compile (PR 3 note) — a retrace in the measured
        # window would charge compilation to whichever mode runs it.
        eng.submit(workload[0], max_new=2 * eng.chunk + 2)
        eng.run()
        guard = RecompileGuard()
        guard.track("decode", eng._decode)
        guard.track("prefill", eng._prefill)
        guard.snapshot()
        return eng, guard

    def wave(eng):
        t0 = time.perf_counter()
        ids = [eng.submit(p, max_new=max_new) for p in workload]
        done = eng.run()
        return [done[i] for i in ids], time.perf_counter() - t0

    tr = Tracer(capacity=1 << 17)
    eng_off, _ = setup(None)
    eng_on, guard_on = setup(tr)
    walls_off, walls_on = [], []
    toks_off = toks_on = None
    for _ in range(repeats):                     # interleaved best-of-N:
        toks_off, w = wave(eng_off)              # machine drift hits both
        walls_off.append(w)                      # modes alike, min() takes
        toks_on, w = wave(eng_on)                # the clean floor of each
        walls_on.append(w)
    misses_on = guard_on.misses_since()
    tok_s_off = n_req * max_new / min(walls_off)
    tok_s_on = n_req * max_new / min(walls_on)
    overhead = 1.0 - tok_s_on / tok_s_off

    # Speculative traced wave: verify + rewind spans (random prompts
    # reject everything — 0-accept full rewinds) for phase coverage.
    eng_spec = ContinuousBatcher(params, cfg, kv_layout="paged",
                                 speculative=True, gamma=2, tracer=tr,
                                 **eng_kw)
    for p in workload[:4]:
        eng_spec.submit(p, max_new=6)
    eng_spec.run()

    spans = tr.spans()
    path = os.path.join(tempfile.gettempdir(), "obs_trace_perfetto.json")
    doc = write_perfetto(spans, path)    # validate the document WE wrote
    problems = validate_perfetto(doc)
    names = {s.name for s in spans}
    want = {"queue", "admit", "prefill", "decode_chunk", "verify",
            "rewind", "reap"}
    extra = {
        "obs_shape": f"{n_req} reqs, max_new {max_new}, best-of-{repeats} "
                     f"walls per mode",
        "obs_interpret": not on_tpu,
        "obs_tok_s_off": round(tok_s_off, 1),
        "obs_tok_s_on": round(tok_s_on, 1),
        "obs_overhead_frac": round(overhead, 4),
        "obs_overhead_ok": overhead < 0.02,
        "obs_token_identity": toks_on == toks_off,
        "obs_zero_retrace": not any(misses_on.values()),
        "obs_spans": len(spans),
        "obs_spans_dropped": tr.dropped,
        "obs_phases_present": sorted(want & names) == sorted(want),
        "obs_phases_missing": sorted(want - names),
        "obs_perfetto_valid": not problems,
        "obs_perfetto_problems": problems[:5],
        "obs_perfetto_path": path,
        "obs_decode_chunk_spans": sum(
            1 for s in spans if s.name == "decode_chunk"
            and s.lane == "engine"),
    }
    return {
        "metric": "obs_overhead",
        "value": extra["obs_overhead_frac"],
        "unit": "frac",
        "extra": extra,
    }


def bench_fleet(smoke=False):
    """Fleet-serving leg — the cache-aware router (fleet/router.py)
    measured against round-robin placement on the SAME open-loop
    Poisson trace: K hot system prompts (the shared-prefix workload of
    the prefix-cache leg) arrive across 3 paged replicas; the affinity
    policy must beat round-robin on aggregate prefix-hit rate (the CI
    assert), a forced mid-trace shed must migrate in-flight requests to
    the coldest replica, and EVERY stream — migrated or not, either
    policy — must be byte-equal to a single-engine reference run
    (greedy streams are placement-independent; the fleet must not
    change answers, only where they compute). Reports fleet tok/s,
    per-class TTFT p50, both hit rates, and the migration count. On CPU
    (or --smoke) the model is tiny/f32; the TPU run under the driver is
    what BENCH_*.json captures."""
    import dataclasses

    import numpy as np

    import jax
    import jax.numpy as jnp

    from k8s_gpu_scheduler_tpu.fleet import FleetError, Router
    from k8s_gpu_scheduler_tpu.metrics.exporter import (
        FLEET_MIGRATED_TOTAL, FLEET_ROUTED_TOTAL, Registry,
    )
    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

    on_tpu = jax.devices()[0].platform == "tpu"
    if smoke or not on_tpu:
        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
        n_req, max_new, rate = 30, 10, 1.5
        eng_kw = dict(n_slots=4, max_len=96, chunk=4, prefill_bucket=16,
                      kv_layout="paged", page_size=8, prefix_cache=True)
    else:
        cfg = LlamaConfig(
            vocab=32000, d_model=1024, n_layers=4, n_heads=16,
            n_kv_heads=16, d_ff=4096, max_seq=2048, remat=False,
            decode_attn="fused")
        n_req, max_new, rate = 96, 48, 2.0
        eng_kw = dict(n_slots=8, max_len=2048, chunk=8,
                      prefill_bucket=128, kv_layout="paged", page_size=64,
                      kv_dtype="int8", prefix_cache=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    n_replicas, n_classes = 3, 3
    page = eng_kw["page_size"]
    rng = np.random.default_rng(0)
    hot = [list(rng.integers(0, cfg.vocab, 2 * page))
           for _ in range(n_classes)]
    # Random class order: a round-robin class schedule would let the
    # round-robin BASELINE partition classes onto replicas by accident.
    classes = [int(c) for c in rng.integers(0, n_classes, n_req)]
    workload = [hot[c] + list(rng.integers(0, cfg.vocab, 3 + i % 7))
                for i, c in enumerate(classes)]
    # One Poisson arrival schedule (in router-step units) for BOTH
    # policies — the comparison is placement, not traffic.
    arrive_step = np.floor(np.cumsum(
        rng.exponential(1.0 / rate, n_req))).astype(int)

    def engines():
        return [(f"r{i}", ContinuousBatcher(params, cfg, **eng_kw))
                for i in range(n_replicas)]

    # Single-engine reference: greedy streams do not depend on where
    # they decode, so one engine's answers are every placement's truth.
    ref_eng = ContinuousBatcher(params, cfg, **eng_kw)
    ids = [ref_eng.submit(p, max_new=max_new) for p in workload]
    ref_done = {}
    while ref_eng.pending:
        ref_done.update(ref_eng.step())
    ref = [ref_done[i] for i in ids]

    def drive(policy, shed_at=None):
        """Run the trace through a fresh fleet; returns (streams in
        submit order, router, migrated count, wall seconds)."""
        reg = Registry()
        router = Router(engines(), policy=policy, metrics=reg)
        frids, done, migrated = [], {}, 0
        nxt, t = 0, 0
        t0 = time.perf_counter()
        while nxt < n_req or router.pending:
            while nxt < n_req and arrive_step[nxt] <= t:
                frids.append(router.submit(workload[nxt],
                                           max_new=max_new))
                nxt += 1
            done.update(router.step())
            if shed_at is not None and nxt >= shed_at and migrated == 0:
                stats = {r: rep.engine.replica_stats()
                         for r, rep in router._replicas.items()}
                src = max(stats,
                          key=lambda r: (stats[r]["active_slots"], r))
                dst = min(stats,
                          key=lambda r: (stats[r]["active_slots"], r))
                if src != dst and stats[src]["active_slots"] > 1:
                    try:
                        migrated = router.shed(src, dst)
                    except FleetError:
                        pass        # target tight this instant: retry
            t += 1
        wall = time.perf_counter() - t0
        streams = [done[f] for f in frids]
        return streams, router, reg, migrated, wall

    aff, aff_router, aff_reg, migrated, aff_wall = drive(
        "affinity", shed_at=n_req // 2)
    rr, rr_router, _, _, rr_wall = drive("round_robin")

    aff_stats = aff_router.stats()
    rr_stats = rr_router.stats()
    # Per-class TTFT over the affinity run (the metrics drained during
    # step() ride the router's fleet-id records).
    met = aff_router.pop_request_metrics()
    ttft_by_class = {c: [] for c in range(n_classes)}
    for frid, m in met.items():
        ttft_by_class[classes[frid]].append(m["ttft_s"] * 1e3)
    ttft_p50 = {f"class{c}": round(_pctl(v, 0.50), 2) if v else None
                for c, v in ttft_by_class.items()}

    n_tok = sum(len(s) for s in aff)
    extra = {
        "fleet_shape": f"{n_replicas} replicas, {n_req} reqs over "
                       f"{n_classes} hot {2 * page}-tok prompts, "
                       f"max_new {max_new}, Poisson rate {rate}/step",
        "fleet_interpret": not on_tpu,
        "fleet_tok_s": round(n_tok / aff_wall, 1),
        "fleet_rr_tok_s": round(n_tok / rr_wall, 1),
        "fleet_prefix_hit_rate": round(
            aff_stats["aggregate_prefix_hit_rate"], 4),
        "fleet_rr_prefix_hit_rate": round(
            rr_stats["aggregate_prefix_hit_rate"], 4),
        "fleet_hit_beats_rr": (aff_stats["aggregate_prefix_hit_rate"]
                               > rr_stats["aggregate_prefix_hit_rate"]),
        "fleet_token_identity": aff == ref and rr == ref,
        "fleet_migrated_requests": migrated,
        "fleet_degraded_routes": aff_stats["degraded_routes"],
        "fleet_ttft_p50_ms": ttft_p50,
        "fleet_routed_total": sum(
            aff_reg.counter(FLEET_ROUTED_TOTAL).value(
                replica=f"r{i}", policy=p)
            for i in range(n_replicas)
            for p in ("affinity", "degraded")),
        "fleet_migrated_total": sum(
            aff_reg.counter(FLEET_MIGRATED_TOTAL).value(
                replica=f"r{i}") for i in range(n_replicas)),
    }
    return {
        "metric": "fleet_bench",
        "value": extra["fleet_prefix_hit_rate"],
        "unit": "hit_rate",
        "extra": extra,
    }


def bench_fleet_chaos(smoke=False):
    """Fleet crash-tolerance leg — the zero-loss contract of the
    crash-tolerant router (fleet/health.py + fleet/journal.py +
    deterministic-replay failover), measured: an open-loop Poisson trace
    runs over 3 paged replicas while a SEEDED schedule hard-kills
    replicas mid-trace (``replica.crash`` kind="crash": the engine
    object is discarded — no drain, no snapshot; quarantined replicas
    rejoin through the engine factory on a jittered backoff). The CI
    asserts: every submitted request completes; every delivered stream
    is byte-equal to the no-fault single-engine reference (journal
    replay is token-identical, verify-window checked);
    ``tpu_fleet_requests_lost_total == 0``; replayed (re-decoded verify)
    tokens are bounded by journaled delivered tokens; and the whole
    chaos run — kills, failovers, rejoins, streams — is
    replay-deterministic (two runs, identical injector logs and
    results). On CPU (or --smoke) the model is tiny/f32; the TPU run
    under the driver is what BENCH_*.json captures."""
    import dataclasses

    import numpy as np

    import jax
    import jax.numpy as jnp

    from k8s_gpu_scheduler_tpu.fleet import HealthPolicy, Router
    from k8s_gpu_scheduler_tpu.metrics.exporter import (
        FLEET_FAILOVERS_TOTAL, FLEET_LOST_TOTAL,
        FLEET_REPLAYED_TOKENS_TOTAL, Registry,
    )
    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
    from k8s_gpu_scheduler_tpu.testing.faults import (
        FaultInjector, FaultRule,
    )
    from k8s_gpu_scheduler_tpu.utils.retry import RetryPolicy

    on_tpu = jax.devices()[0].platform == "tpu"
    if smoke or not on_tpu:
        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
        n_req, max_new, rate = 24, 10, 1.5
        eng_kw = dict(n_slots=4, max_len=96, chunk=4, prefill_bucket=16,
                      kv_layout="paged", page_size=8, prefix_cache=True)
        crash_p, crash_until = 0.02, 60
    else:
        cfg = LlamaConfig(
            vocab=32000, d_model=1024, n_layers=4, n_heads=16,
            n_kv_heads=16, d_ff=4096, max_seq=2048, remat=False,
            decode_attn="fused")
        n_req, max_new, rate = 96, 48, 2.0
        eng_kw = dict(n_slots=8, max_len=2048, chunk=8,
                      prefill_bucket=128, kv_layout="paged", page_size=64,
                      kv_dtype="int8", prefix_cache=True)
        crash_p, crash_until = 0.01, 400
    params = init_params(cfg, jax.random.PRNGKey(0))
    n_replicas, n_classes = 3, 3
    page = eng_kw["page_size"]
    rng = np.random.default_rng(0)
    hot = [list(rng.integers(0, cfg.vocab, 2 * page))
           for _ in range(n_classes)]
    classes = [int(c) for c in rng.integers(0, n_classes, n_req)]
    workload = [hot[c] + list(rng.integers(0, cfg.vocab, 3 + i % 7))
                for i, c in enumerate(classes)]
    arrive_step = np.floor(np.cumsum(
        rng.exponential(1.0 / rate, n_req))).astype(int)

    def factory(rid):
        return ContinuousBatcher(params, cfg, **eng_kw)

    # No-fault single-engine reference: greedy streams are
    # placement-independent, so one engine's answers are the truth the
    # chaos run must reproduce byte-for-byte.
    ref_eng = factory("ref")
    ids = [ref_eng.submit(p, max_new=max_new) for p in workload]
    ref_done = {}
    while ref_eng.pending:
        ref_done.update(ref_eng.step())
    ref = [ref_done[i] for i in ids]

    def drive():
        """One chaos run: fresh fleet, fresh injector, same seeds. The
        router runs on a VirtualClock advanced a FIXED dt per step, so
        quarantine expiry — and with it the serving set and the
        injector's call sequence — is a pure function of the step
        index, not of how fast this host executes a step (the first
        drive pays JIT compiles the second doesn't; on wall clock the
        two runs would disagree about how many steps a hold spans).
        Returns everything the determinism gate compares."""
        from k8s_gpu_scheduler_tpu.obs import VirtualClock

        clock = VirtualClock()
        inj = FaultInjector(seed=13, rules=[
            # Seeded probabilistic kills while the trace is in flight —
            # a pure function of (seed, call sequence), so two runs
            # inject at the same points. The window keeps the tail of
            # the run kill-free so rejoined replicas drain cleanly.
            FaultRule(site="replica.crash", kind="crash", p=crash_p,
                      until=crash_until),
        ])
        reg = Registry()
        router = Router(
            [(f"r{i}", factory(f"r{i}")) for i in range(n_replicas)],
            metrics=reg, engine_factory=factory, faults=inj,
            clock=clock,
            health=HealthPolicy(quarantine=RetryPolicy(
                attempts=8, base_s=0.05, multiplier=2.0, max_s=0.2,
                jitter=0.5)),
            health_seed=13)
        frids, done = [], {}
        nxt, t = 0, 0
        t0 = time.perf_counter()
        while nxt < n_req or router.pending:
            while nxt < n_req and arrive_step[nxt] <= t:
                frids.append(router.submit(workload[nxt],
                                           max_new=max_new))
                nxt += 1
            done.update(router.step())
            clock.advance(0.02)          # one step = 20 virtual ms
            t += 1
        wall = time.perf_counter() - t0
        streams = [done.get(f) for f in frids]
        st = router.stats()
        lost = sum(reg.counter(FLEET_LOST_TOTAL).value(replica=f"r{i}")
                   for i in range(n_replicas)) \
            + reg.counter(FLEET_LOST_TOTAL).value()
        failovers = sum(
            reg.counter(FLEET_FAILOVERS_TOTAL).value(replica=f"r{i}")
            for i in range(n_replicas))
        replayed = reg.counter(FLEET_REPLAYED_TOKENS_TOTAL).value()
        return (streams, list(inj.log), st, lost, failovers, replayed,
                wall)

    streams, log, st, lost, failovers, replayed, wall = drive()
    streams2, log2, st2, lost2, _fo2, _rp2, _w2 = drive()

    n_tok = sum(len(s) for s in streams if s)
    extra = {
        "fleet_chaos_shape": f"{n_replicas} replicas, {n_req} reqs over "
                             f"{n_classes} hot {2 * page}-tok prompts, "
                             f"max_new {max_new}, Poisson rate "
                             f"{rate}/step, crash p={crash_p} "
                             f"until={crash_until}",
        "fleet_chaos_interpret": not on_tpu,
        "fleet_chaos_tok_s": round(n_tok / wall, 1),
        "fleet_chaos_completed": all(s is not None for s in streams),
        "fleet_chaos_token_identity": streams == ref,
        "fleet_chaos_requests_lost": lost,
        "fleet_chaos_failovers": failovers,
        "fleet_chaos_kills": sum(1 for s in log if s[2] == "crash"),
        "fleet_chaos_replayed_tokens": replayed,
        "fleet_chaos_delivered_tokens": st["journal_delivered_tokens"],
        # Bounded rework: the re-decoded verify window can never exceed
        # what the journal had delivered (per failover it is
        # min(verify_tokens, delivered); summed it stays under the
        # delivered total).
        "fleet_chaos_replay_bounded":
            replayed <= st["journal_delivered_tokens"],
        "fleet_chaos_journal_inflight_end": st["journal_inflight"],
        "fleet_chaos_deterministic": (streams == streams2
                                      and log == log2
                                      and lost == lost2
                                      and st["failovers"]
                                      == st2["failovers"]),
    }
    return {
        "metric": "fleet_chaos_bench",
        "value": failovers,
        "unit": "failovers",
        "extra": extra,
    }


def bench_chunked_prefill(smoke=False):
    """Chunked-prefill leg — the TTFT/decode-interference contract of
    ``ContinuousBatcher(prefill_chunk_tokens=...)``, measured: an
    open-loop Poisson, decode-heavy short-request trace with LONG-PROMPT
    arrivals injected mid-stream runs chunking-off and chunking-on over
    the SAME schedule (step-indexed arrivals, so scheduling — and hence
    the chunk/rung walk — is a pure function of the trace and the
    second, measured pass retraces nothing). Chunking-off, the long
    admission dispatches its whole prefill as one program and every
    active decode slot stalls for it; chunking-on, each step spends at
    most the token budget on prefill chunks before its decode chunk.
    The CI step asserts: byte-identical streams, zero retraces across
    the measured pass, a STRICTLY lower max decode-step stall with
    chunking on, and short-request TTFT p99 no worse (1.1x headroom for
    CPU wall jitter — the observed margin is several-fold the other
    way). On CPU (or --smoke) the model is tiny/f32 with a 512-row rope
    table so the injected prompt is genuinely long; the TPU run under
    the driver is what BENCH_*.json captures."""
    import dataclasses

    import numpy as np

    import jax
    import jax.numpy as jnp

    from k8s_gpu_scheduler_tpu.analysis.recompile import RecompileGuard
    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

    on_tpu = jax.devices()[0].platform == "tpu"
    if smoke or not on_tpu:
        # f32: the identity assert must see no bf16 near-tie noise.
        cfg = dataclasses.replace(LlamaConfig(
            vocab=256, d_model=64, n_layers=2, n_heads=8, n_kv_heads=8,
            d_ff=128, max_seq=512, remat=False), dtype=jnp.float32)
        n_short, short_p, short_new, rate = 16, 12, 32, 0.5
        long_p, long_new, long_at = 320, 8, (5, 14)
        budget = 48
        eng_kw = dict(n_slots=8, max_len=512, chunk=4, prefill_bucket=16,
                      page_size=16)
    else:
        cfg = LlamaConfig(
            vocab=32000, d_model=1024, n_layers=4, n_heads=16,
            n_kv_heads=16, d_ff=4096, max_seq=2048, remat=False,
            decode_attn="fused")
        n_short, short_p, short_new, rate = 48, 64, 64, 1.5
        long_p, long_new, long_at = 1536, 16, (8, 28)
        budget = 256
        eng_kw = dict(n_slots=8, max_len=2048, chunk=8,
                      prefill_bucket=128, page_size=64, kv_dtype="int8")
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    # One step-indexed schedule for BOTH modes: shorts Poisson at
    # ``rate``/step, longs injected while shorts are decoding, plus a
    # deterministic BURST of shorts arriving with each long — the
    # interference scenario the feature targets: chunking off, those
    # shorts' first tokens sit behind the long's whole-prefill dispatch
    # (the TTFT spike the serve_poisson_* p99s show); chunking on, their
    # single-chunk prefills share the same steps' budgets with the
    # long's quanta. Greedy streams depend only on prompts, so identity
    # is schedule-exact.
    arr = np.floor(np.cumsum(
        rng.exponential(1.0 / rate, n_short))).astype(int)
    sched = [(int(s), list(rng.integers(0, cfg.vocab, short_p)),
              short_new, "short") for s in arr]
    for ls in long_at:
        sched.append((ls, list(rng.integers(0, cfg.vocab, long_p)),
                      long_new, "long"))
        for burst_step in (ls, ls + 1):
            for _ in range(2):
                sched.append((burst_step,
                              list(rng.integers(0, cfg.vocab, short_p)),
                              short_new, "short"))
    sched.sort(key=lambda e: e[0])
    n_short += 4 * len(long_at)          # the burst rides the short class

    def drive(eng):
        """One pass of the trace: per-step walls for steps that ran a
        decode/verify dispatch (the decode-step stall series), streams
        in submission order, latency records, peak prefill backlog."""
        done, ids, stalls = {}, [], []
        t = sub = 0
        backlog_peak = 0.0
        while sub < len(sched) or eng.pending:
            while sub < len(sched) and sched[sub][0] <= t:
                ids.append(eng.submit(sched[sub][1],
                                      max_new=sched[sub][2]))
                sub += 1
            if eng.pending:
                seq0 = eng._flight._seq
                t0 = time.perf_counter()
                done.update(eng.step())
                wall = time.perf_counter() - t0
                if any(r["seq"] >= seq0
                       and r["kind"] in ("decode", "verify")
                       for r in eng._flight.records()):
                    stalls.append(wall * 1e3)
                backlog_peak = max(backlog_peak, eng.pool_metrics().get(
                    "prefill_backlog_tokens", 0.0))
            t += 1
        return ([done[i] for i in ids], stalls,
                eng.pop_request_metrics(), ids, backlog_peak)

    kinds = [e[3] for e in sched]
    engines = {}
    for mode, chunk_tokens in (("unchunked", None), ("chunked", budget)):
        eng = ContinuousBatcher(params, cfg, kv_layout="paged",
                                prefill_chunk_tokens=chunk_tokens,
                                **eng_kw)
        drive(eng)                   # warm pass: every rung compiles
        guard = RecompileGuard()
        guard.track("decode", eng._decode)
        guard.track("prefill", eng._prefill)
        guard.snapshot()
        engines[mode] = (eng, guard)
    # Interleaved best-of-N measured passes (the obs-leg pattern):
    # machine drift hits both modes alike, and min() per mode takes
    # each one's clean floor — the max-stall and tail-TTFT statistics
    # are single-step-noise sensitive, the structural gap is not.
    repeats = 2
    passes = {m: [] for m in engines}
    for _ in range(repeats):
        for mode in ("unchunked", "chunked"):
            streams, stalls, met, ids, backlog_peak = drive(
                engines[mode][0])
            ttft = {"short": [], "long": []}
            for j, rid in enumerate(ids):
                if rid in met:
                    ttft[kinds[j]].append(met[rid]["ttft_s"] * 1e3)
            passes[mode].append({
                "streams": streams,
                "max_stall": max(stalls),
                "stall_p99": _pctl(stalls, 0.99),
                "ttft_p50": _pctl(ttft["short"], 0.50),
                "ttft_p99": _pctl(ttft["short"], 0.99),
                "long_p50": _pctl(ttft["long"], 0.50),
                "backlog_peak": backlog_peak,
            })

    def agg(mode):
        ps = passes[mode]
        eng, guard = engines[mode]
        return {
            "streams": ps[0]["streams"],
            "same_streams": all(p["streams"] == ps[0]["streams"]
                                for p in ps),
            "max_stall": min(p["max_stall"] for p in ps),
            "stall_p99": min(p["stall_p99"] for p in ps),
            "ttft_p50": min(p["ttft_p50"] for p in ps),
            "ttft_p99": min(p["ttft_p99"] for p in ps),
            "long_p50": min(p["long_p50"] for p in ps),
            "misses": guard.misses_since(),
            "backlog_peak": max(p["backlog_peak"] for p in ps),
            "chunks": eng.pool_metrics()["prefill_chunks_total"],
        }

    on, off = agg("chunked"), agg("unchunked")
    extra = {
        "chunked_prefill_shape": (
            f"{n_short} shorts ({short_p} tok, max_new {short_new}) at "
            f"{rate}/step + {len(long_at)} x {long_p}-tok longs, "
            f"budget {budget}"),
        "chunked_prefill_interpret": not on_tpu,
        "chunked_prefill_passes": repeats,
        "chunked_token_identity": (on["streams"] == off["streams"]
                                   and on["same_streams"]
                                   and off["same_streams"]),
        "chunked_zero_retrace": not any(on["misses"].values()),
        "unchunked_max_stall_ms": round(off["max_stall"], 1),
        "chunked_max_stall_ms": round(on["max_stall"], 1),
        "unchunked_stall_p99_ms": round(off["stall_p99"], 1),
        "chunked_stall_p99_ms": round(on["stall_p99"], 1),
        "unchunked_ttft_p50_ms": round(off["ttft_p50"], 1),
        "chunked_ttft_p50_ms": round(on["ttft_p50"], 1),
        "unchunked_ttft_p99_ms": round(off["ttft_p99"], 1),
        "chunked_ttft_p99_ms": round(on["ttft_p99"], 1),
        "unchunked_long_ttft_p50_ms": round(off["long_p50"], 1),
        "chunked_long_ttft_p50_ms": round(on["long_p50"], 1),
        "chunked_backlog_peak_tokens": on["backlog_peak"],
        "chunked_prefill_chunks_total": on["chunks"],
    }
    extra["chunked_stall_win"] = (extra["chunked_max_stall_ms"]
                                  < extra["unchunked_max_stall_ms"])
    # 1.1x = CPU wall-jitter headroom on the no-worse bound; the
    # observed margin is several-fold in chunking's favor.
    extra["chunked_ttft_p99_ok"] = (extra["chunked_ttft_p99_ms"]
                                    <= 1.1 * extra["unchunked_ttft_p99_ms"])
    stall_ratio = (extra["unchunked_max_stall_ms"]
                   / max(extra["chunked_max_stall_ms"], 1e-9))
    return {
        "metric": "chunked_prefill_stall_ratio",
        "value": round(stall_ratio, 2),
        "unit": "x",
        "extra": extra,
    }


def bench_disagg(smoke=False):
    """Disaggregated-serving leg — the phase-isolation contract of
    ``Router(pools=...)`` (fleet/router.py + fleet/pools.py), measured:
    the chunked-prefill leg's decode-heavy Poisson trace with injected
    long prompts runs over a COLOCATED fleet (4 mixed replicas, each
    admitting + decoding) and a DISAGGREGATED fleet (2 role='prefill'
    replicas + 2 decode replicas, drain→absorb handoff at the phase
    boundary) on the SAME step-indexed schedule, round-robin placement
    both so the comparison is pure pool structure. Because the
    in-process router serializes every replica onto one thread, walls
    are ENGINE-LOCAL (each replica's own step() wall — what concurrent
    replicas would each observe): the decode-step stall series is each
    decode-capable engine's per-step wall, and TPOT is per-request
    decode elapsed on the OWNING engine's clock / tokens it decoded
    there. Colocated, a long admission's whole prefill lands inside a
    decode engine's step and every co-resident stream eats it; disagg,
    the decode pool never dispatches prefill at all, so its stall
    ceiling is one decode chunk. The CI step asserts byte-identical
    streams vs a single-engine reference, zero retrace on both pools
    across the measured passes, requests_lost == 0, every request
    handed off, STRICTLY lower max decode-step stall and TPOT p99 for
    disagg, and a valid Perfetto export carrying the full
    prefill_chunk → handoff → decode_chunk lifecycle under one rid. On
    CPU (or --smoke) the model is tiny/f32; the TPU run under the
    driver is what BENCH_*.json captures."""
    import dataclasses
    import os
    import tempfile

    import numpy as np

    import jax
    import jax.numpy as jnp

    from k8s_gpu_scheduler_tpu.analysis.recompile import RecompileGuard
    from k8s_gpu_scheduler_tpu.fleet import Router
    from k8s_gpu_scheduler_tpu.metrics.exporter import Registry
    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
    from k8s_gpu_scheduler_tpu.obs import (
        Tracer, validate_perfetto, write_perfetto,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if smoke or not on_tpu:
        # f32: the identity assert must see no bf16 near-tie noise.
        cfg = dataclasses.replace(LlamaConfig(
            vocab=256, d_model=64, n_layers=2, n_heads=8, n_kv_heads=8,
            d_ff=128, max_seq=512, remat=False), dtype=jnp.float32)
        n_short, short_p, short_new, rate = 14, 12, 40, 1.0
        long_p, long_new, long_at = 256, 8, (6, 14)
        chunk_budget = 32
        eng_kw = dict(n_slots=6, max_len=320, chunk=4, prefill_bucket=16,
                      kv_layout="paged", page_size=16, prefix_cache=False)
    else:
        cfg = LlamaConfig(
            vocab=32000, d_model=1024, n_layers=4, n_heads=16,
            n_kv_heads=16, d_ff=4096, max_seq=2048, remat=False,
            decode_attn="fused")
        n_short, short_p, short_new, rate = 38, 64, 64, 2.0
        long_p, long_new, long_at = 1024, 16, (8, 20)
        chunk_budget = 256
        eng_kw = dict(n_slots=8, max_len=2048, chunk=8,
                      prefill_bucket=128, kv_layout="paged", page_size=64,
                      kv_dtype="int8", prefix_cache=False)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    # One step-indexed schedule for BOTH fleets (the chunked-prefill
    # leg's interference trace): shorts Poisson at ``rate``/step, longs
    # injected while shorts decode, a burst of shorts with each long.
    # The total submission count is a multiple of every pool width so
    # the round-robin cursor returns to zero each pass and placement —
    # hence every compiled rung — is identical across the warm and
    # measured passes.
    arr = np.floor(np.cumsum(
        rng.exponential(1.0 / rate, n_short))).astype(int)
    sched = [(int(s), list(rng.integers(0, cfg.vocab, short_p)),
              short_new, "short") for s in arr]
    for ls in long_at:
        sched.append((ls, list(rng.integers(0, cfg.vocab, long_p)),
                      long_new, "long"))
        for burst_step in (ls, ls + 1):
            for _ in range(2):
                sched.append((burst_step,
                              list(rng.integers(0, cfg.vocab, short_p)),
                              short_new, "short"))
    sched.sort(key=lambda e: e[0])
    n_req = len(sched)
    assert n_req % 4 == 0, "trace must divide every rr pool width"

    # Single-engine reference: greedy streams do not depend on which
    # pool decodes them — one mixed engine's answers are the truth for
    # both fleets (and for the handoff itself).
    ref_eng = ContinuousBatcher(params, cfg, **eng_kw)
    ref_ids = [ref_eng.submit(p, max_new=mn) for _, p, mn, _ in sched]
    ref_done = {}
    while ref_eng.pending:
        ref_done.update(ref_eng.step())
    ref = [ref_done[i] for i in ref_ids]

    def instrument(eng, stalls, vwall, rid):
        """Wrap ``eng.step`` with the engine-local clocks: per-step wall
        appended to ``stalls[rid]`` when the step ran a decode/verify
        dispatch, and accumulated into ``vwall[rid]`` always — the
        virtual own-thread clock a concurrently-deployed replica would
        read (the in-process router serializes replicas, so host wall
        across a router step charges every replica for its peers)."""
        orig = eng.step

        def step():
            seq0 = eng._flight._seq
            t0 = time.perf_counter()
            out = orig()
            wall = time.perf_counter() - t0
            vwall[rid] += wall
            if any(r["seq"] >= seq0
                   and r["kind"] in ("decode", "verify")
                   for r in eng._flight.records()):
                stalls[rid].append(wall * 1e3)
            return out

        eng.step = step

    def build(mode):
        tr = Tracer(capacity=1 << 16)
        reg = Registry()
        stalls, vwall = {}, {}
        if mode == "disagg":
            reps = (
                [(f"p{i}", ContinuousBatcher(
                    params, cfg, role="prefill",
                    prefill_chunk_tokens=chunk_budget, tracer=tr,
                    **eng_kw)) for i in range(2)]
                + [(f"d{i}", ContinuousBatcher(
                    params, cfg, role="decode", tracer=tr, **eng_kw))
                   for i in range(2)])
            pools = {"prefill": ["p0", "p1"], "decode": ["d0", "d1"]}
            measured = ("d0", "d1")
        else:
            reps = [(f"m{i}", ContinuousBatcher(params, cfg, tracer=tr,
                                                **eng_kw))
                    for i in range(4)]
            pools, measured = None, ("m0", "m1", "m2", "m3")
        for rid, eng in reps:
            stalls[rid], vwall[rid] = [], 0.0
            instrument(eng, stalls, vwall, rid)
        router = Router(reps, pools=pools, policy="round_robin",
                        tracer=tr, metrics=reg)
        guards = {}
        for rid, eng in reps:
            g = RecompileGuard()
            g.track("decode", eng._decode)
            g.track("prefill", eng._prefill)
            guards[rid] = g
        return {"router": router, "tracer": tr, "reg": reg,
                "stalls": stalls, "vwall": vwall, "guards": guards,
                "measured": measured}

    def drive(fl):
        """One pass of the trace through ``fl``; engine-local stall
        series reset per pass, TPOT computed on each request's OWNING
        engine's virtual clock (the decode replica after a handoff)."""
        rtr, stalls, vwall = fl["router"], fl["stalls"], fl["vwall"]
        for s in stalls.values():
            s.clear()
        frids, done, tpot = [], {}, {}
        track, last_owner, owner_at = {}, {}, {}
        plan_peak, plan_scale_up = 0, False
        nxt, t = 0, 0
        t0 = time.perf_counter()
        while nxt < n_req or rtr.pending:
            while nxt < n_req and sched[nxt][0] <= t:
                frids.append(rtr.submit(sched[nxt][1],
                                        max_new=sched[nxt][2],
                                        trace_id=f"rq{nxt:03d}"))
                nxt += 1
            new = rtr.step()
            for frid, toks in new.items():
                o = last_owner.get(frid)
                v0, n0 = track.get(frid, {}).get(o, (None, None))
                if v0 is not None and len(toks) - n0 >= 4:
                    tpot[frid] = ((vwall[o] - v0) / (len(toks) - n0)
                                  * 1e3)
            done.update(new)
            for frid in frids:
                if frid in done:
                    continue
                loc = rtr._where.get(frid)
                if loc is None:
                    continue
                owner = loc[0]
                ntok = len(rtr.journal.stream(frid))
                if ntok >= 1 and owner not in track.setdefault(frid, {}):
                    track[frid][owner] = (vwall[owner], ntok)
                last_owner[frid] = owner
            if rtr._pools is not None:
                plan = rtr.pool_plan()
                plan_peak = max(plan_peak,
                                plan.prefill_replicas_desired)
                plan_scale_up = plan_scale_up or plan.decode_scale_up
            t += 1
        wall = time.perf_counter() - t0
        streams = [done[f] for f in frids]
        met = rtr.pop_request_metrics()
        ttft = [met[f]["ttft_s"] * 1e3 for f in frids if f in met]
        pool_stalls = [w for rid in fl["measured"]
                       for w in stalls[rid]]
        return {
            "streams": streams,
            "max_stall": max(pool_stalls),
            "stall_p99": _pctl(pool_stalls, 0.99),
            "tpot_p99": _pctl(list(tpot.values()), 0.99),
            "tpot_p50": _pctl(list(tpot.values()), 0.50),
            "ttft_p99": _pctl(ttft, 0.99),
            "wall": wall,
            "plan_peak": plan_peak,
            "plan_scale_up": plan_scale_up,
        }

    fleets = {m: build(m) for m in ("colocated", "disagg")}
    for m in fleets:
        drive(fleets[m])             # warm pass: every rung compiles
        for g in fleets[m]["guards"].values():
            g.snapshot()
    # Interleaved best-of-N measured passes (the chunked-prefill-leg
    # pattern): machine drift hits both fleets alike; min() per fleet
    # takes each one's clean floor.
    repeats = 2
    passes = {m: [] for m in fleets}
    for _ in range(repeats):
        for m in ("colocated", "disagg"):
            passes[m].append(drive(fleets[m]))

    def agg(mode):
        ps = passes[mode]
        misses = {rid: g.misses_since()
                  for rid, g in fleets[mode]["guards"].items()}
        st = fleets[mode]["router"].stats()
        return {
            "streams": ps[0]["streams"],
            "same_streams": all(p["streams"] == ps[0]["streams"]
                                for p in ps),
            "max_stall": min(p["max_stall"] for p in ps),
            "stall_p99": min(p["stall_p99"] for p in ps),
            "tpot_p99": min(p["tpot_p99"] for p in ps),
            "tpot_p50": min(p["tpot_p50"] for p in ps),
            "ttft_p99": min(p["ttft_p99"] for p in ps),
            "retraces": sum(n for m_ in misses.values()
                            for n in m_.values()),
            "lost": st["requests_lost"],
            "handoffs": st["handoffs"],
            "plan_peak": max(p["plan_peak"] for p in ps),
            "plan_scale_up": any(p["plan_scale_up"] for p in ps),
        }

    dis, col = agg("disagg"), agg("colocated")
    # Perfetto artifact from the disagg run: the handed-off request's
    # prefill_chunk (prefill replica) → handoff (router lane) →
    # decode_chunk (decode replica) phases correlate under ONE rid via
    # the trace_id relabel absorb applies.
    spans = fleets["disagg"]["tracer"].spans()
    by_rid = {}
    for s in spans:
        if s.rid is not None:
            by_rid.setdefault(s.rid, set()).add(s.name)
    lifecycle = {"prefill_chunk", "handoff", "decode_chunk"}
    phases_ok = any(lifecycle <= names for names in by_rid.values())
    perfetto_path = os.path.join(tempfile.gettempdir(),
                                 "disagg_trace_perfetto.json")
    doc = write_perfetto(spans, perfetto_path)
    problems = validate_perfetto(doc)
    handoff_ms = [(s.t1 - s.t0) * 1e3 for s in spans
                  if s.name == "handoff"]

    extra = {
        "disagg_shape": (
            f"{n_req - len(long_at)} shorts ({short_p} tok, max_new "
            f"{short_new}) at {rate}/step + {len(long_at)} x "
            f"{long_p}-tok longs; 2 prefill (chunk {chunk_budget}) + "
            f"2 decode vs 4 mixed"),
        "disagg_interpret": not on_tpu,
        "disagg_passes": repeats,
        "disagg_token_identity": (dis["streams"] == ref
                                  and col["streams"] == ref
                                  and dis["same_streams"]
                                  and col["same_streams"]),
        "disagg_zero_retrace": dis["retraces"] == 0,
        "colocated_retraces": col["retraces"],
        "disagg_requests_lost": dis["lost"],
        "colocated_requests_lost": col["lost"],
        # warm + measured passes all hand every request off exactly once
        "disagg_handoffs_total": dis["handoffs"],
        "disagg_all_handed_off": (
            dis["handoffs"] == (repeats + 1) * n_req),
        "colocated_max_stall_ms": round(col["max_stall"], 1),
        "disagg_max_stall_ms": round(dis["max_stall"], 1),
        "colocated_stall_p99_ms": round(col["stall_p99"], 1),
        "disagg_stall_p99_ms": round(dis["stall_p99"], 1),
        "colocated_tpot_p99_ms": round(col["tpot_p99"], 2),
        "disagg_tpot_p99_ms": round(dis["tpot_p99"], 2),
        "colocated_tpot_p50_ms": round(col["tpot_p50"], 2),
        "disagg_tpot_p50_ms": round(dis["tpot_p50"], 2),
        "colocated_ttft_p99_ms": round(col["ttft_p99"], 1),
        "disagg_ttft_p99_ms": round(dis["ttft_p99"], 1),
        "disagg_handoff_p50_ms": round(_pctl(handoff_ms, 0.50), 2),
        "disagg_handoff_p99_ms": round(_pctl(handoff_ms, 0.99), 2),
        "disagg_plan_prefill_desired_peak": dis["plan_peak"],
        "disagg_plan_decode_scale_up": dis["plan_scale_up"],
        "disagg_perfetto_valid": not problems and phases_ok,
        "disagg_perfetto_path": perfetto_path,
        "disagg_perfetto_spans": len(spans),
    }
    extra["disagg_stall_win"] = (extra["disagg_max_stall_ms"]
                                 < extra["colocated_max_stall_ms"])
    extra["disagg_tpot_win"] = (extra["disagg_tpot_p99_ms"]
                                < extra["colocated_tpot_p99_ms"])
    stall_ratio = (extra["colocated_max_stall_ms"]
                   / max(extra["disagg_max_stall_ms"], 1e-9))
    return {
        "metric": "disagg_stall_ratio",
        "value": round(stall_ratio, 2),
        "unit": "x",
        "extra": extra,
    }


def bench_multiturn(smoke=False):
    """Multi-turn serving leg — the prefix-attention prefill kernel +
    decoded-suffix caching, measured end-to-end: N conversations × K
    turns (each turn's prompt IS the whole prior transcript + new user
    text) driven over the SAME trace through four engine configs —
    kernel-on/donation-on (the feature), gather/donation-on (the
    kernel A/B: same reuse, materializing prefix attention),
    kernel-on/donation-off (the reuse A/B: PR 4's prompt-only
    donation), and a warm pass of the feature config under a
    RecompileGuard. Greedy streams must be identical across all
    configs (the trace is then genuinely shared), the measured pass
    must be zero-retrace (hit lengths/tables/donated content vary,
    the compiled (tb, hb) rungs must not), turn-2+ prefill tokens
    skipped with donation on must strictly beat the prompt-only
    baseline (each turn re-prefilling its own previous answer is
    exactly the waste the donation removes), and the warmed-cache
    turn-2+ TTFT p50 must be strictly lower. On CPU (or --smoke) the
    model is tiny/f32 with the kernel interpreted; the TPU run under
    the driver is what BENCH_*.json captures."""
    import dataclasses

    import numpy as np

    import jax
    import jax.numpy as jnp

    from k8s_gpu_scheduler_tpu.analysis.recompile import RecompileGuard
    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import (
        ContinuousBatcher, decode_fallback_counts,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if smoke or not on_tpu:
        # f32: the identity assert must see no bf16 near-tie noise.
        cfg = dataclasses.replace(LlamaConfig.tiny(),
                                  decode_attn="fused", dtype=jnp.float32)
        n_conv, n_turns, p1_len, user_len, turn_new = 4, 3, 16, 8, 24
        eng_kw = dict(n_slots=4, max_len=128, chunk=4, prefill_bucket=8,
                      page_size=8)
    else:
        cfg = LlamaConfig(
            vocab=32000, d_model=1024, n_layers=4, n_heads=16,
            n_kv_heads=16, d_ff=4096, max_seq=4096, remat=False,
            decode_attn="fused")
        n_conv, n_turns, p1_len, user_len, turn_new = 8, 4, 192, 64, 128
        eng_kw = dict(n_slots=8, max_len=4096, chunk=16,
                      prefill_bucket=128, page_size=64)
    params = init_params(cfg, jax.random.PRNGKey(0))

    def user_text(rng, turn):
        return list(rng.integers(0, cfg.vocab,
                                 p1_len if turn == 0 else user_len))

    def drive(prefill_attn, donate, guard=None):
        """All N conversations advance turn-by-turn (turn k of every
        conversation batches together). Returns (replies per conv,
        engine, wall seconds, turn-2+ request metrics)."""
        eng = ContinuousBatcher(params, cfg, kv_dtype="int8",
                                kv_layout="paged", prefix_cache=True,
                                prefill_attn=prefill_attn,
                                donate_decoded=donate, **eng_kw)
        # Warm pass: ONE extra conversation walks every turn's (tb, hb)
        # rung end-to-end, outside the measured window.
        wrng = np.random.default_rng(99)
        transcript = []
        for turn in range(n_turns):
            prompt = transcript + user_text(wrng, turn)
            eng.submit(prompt, max_new=turn_new)
            done = {}
            while eng.pending:
                done.update(eng.step())
            (_, toks), = done.items()
            transcript = prompt + toks
        eng.pop_request_metrics()
        warm = eng.pool_metrics()
        if guard is not None:
            guard.track("decode", eng._decode)
            guard.track("prefill", eng._prefill)
            guard.snapshot()
        rngs = [np.random.default_rng(i) for i in range(n_conv)]
        transcripts = [[] for _ in range(n_conv)]
        replies = [[] for _ in range(n_conv)]
        turn_metrics: dict = {}
        t0 = time.perf_counter()
        for turn in range(n_turns):
            rids = {}
            for c in range(n_conv):
                prompt = transcripts[c] + user_text(rngs[c], turn)
                rids[eng.submit(prompt, max_new=turn_new)] = (c, prompt)
            done = {}
            while eng.pending:
                done.update(eng.step())
            for rid, (c, prompt) in rids.items():
                replies[c].append(done[rid])
                transcripts[c] = prompt + done[rid]
            if turn >= 1:
                turn_metrics.update(eng.pop_request_metrics())
            else:
                eng.pop_request_metrics()
        wall = time.perf_counter() - t0
        eng._alloc.assert_consistent()
        return replies, eng, warm, wall, turn_metrics

    guard = RecompileGuard()
    rep_on, eng_on, warm_on, wall_on, met_on = drive("kernel", True, guard)
    retraces = sum(guard.misses_since().values())
    rep_ga, _, _, wall_ga, _ = drive("gather", True)
    rep_off, eng_off, warm_off, wall_off, met_off = drive("kernel", False)
    identity = rep_on == rep_ga == rep_off

    m_on, m_off = eng_on.pool_metrics(), eng_off.pool_metrics()
    skipped_on = m_on["prefill_tokens_skipped"] \
        - warm_on["prefill_tokens_skipped"]
    skipped_off = m_off["prefill_tokens_skipped"] \
        - warm_off["prefill_tokens_skipped"]
    # Per-conversation reuse floor: turn 2 must mount at least turn 1's
    # prompt + decoded full pages (the acceptance criterion's bound).
    ps = eng_kw["page_size"]
    turn1_conv = p1_len + turn_new - 1
    floor = n_conv * ((turn1_conv // ps) * ps)
    total_tokens = n_conv * n_turns * turn_new
    extra = {
        "multiturn_shape": f"{n_conv} convs x {n_turns} turns "
                           f"(p1 {p1_len} + user {user_len}, "
                           f"{turn_new} new/turn)",
        "multiturn_interpret": not on_tpu,
        "multiturn_token_identity": bool(identity),
        "multiturn_retraces": int(retraces),
        "multiturn_tokens_skipped": skipped_on,
        "multiturn_tokens_skipped_prompt_only": skipped_off,
        "multiturn_skip_floor": floor,
        "multiturn_decoded_pages_donated":
            m_on["decoded_pages_donated_total"],
        "multiturn_tok_s_kernel": round(total_tokens / wall_on, 1),
        "multiturn_tok_s_gather": round(total_tokens / wall_ga, 1),
        "multiturn_tok_s_prompt_only": round(total_tokens / wall_off, 1),
        "multiturn_fallbacks": int(sum(
            decode_fallback_counts().values())),
    }
    extra.update(_latency_stats(met_on, prefix="multiturn_warm_"))
    extra.update(_latency_stats(met_off, prefix="multiturn_prompt_only_"))
    return {
        "metric": "multiturn_bench",
        "value": extra["multiturn_warm_ttft_p50_ms"],
        "unit": "ms_warm_ttft_p50",
        "extra": extra,
    }


def bench_kv_tiering(smoke=False):
    """KV-tiering leg — the host-DRAM second tier under the radix tree,
    measured end-to-end: N distinct conversations sized so their cached
    pages OVERFLOW the HBM page pool but FIT the DRAM tier, driven twice
    over the SAME two-turn trace — tiering on (evicted pages demote to
    DRAM, turn 2 promotes them back ahead of prefill) and tiering off
    (eviction forgets the pages, turn 2 re-prefills cold). Greedy
    streams must be identical across both configs (tiering must never
    change an answer), the tiering-on pass must be zero-retrace under a
    RecompileGuard (promotion re-uploads land in fresh pool pages
    BEFORE the prefill dispatch, so the compiled rungs never see the
    tier), the measured request hit rate with tiering on must strictly
    beat the tiering-off ceiling (the pool is too small for resident
    reuse alone — that gap IS the feature), and the promoted-path
    turn-2 TTFT p50 must strictly beat the tiering-off turn-2 TTFT p50
    over the same prompts (re-upload must be cheaper than re-prefill).
    On CPU (or --smoke) the model is tiny/f32; the TPU run under the
    driver is what BENCH_*.json captures."""
    import dataclasses

    import numpy as np

    import jax
    import jax.numpy as jnp

    from k8s_gpu_scheduler_tpu.analysis.recompile import RecompileGuard
    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

    on_tpu = jax.devices()[0].platform == "tpu"
    if smoke or not on_tpu:
        # f32: the identity assert must see no bf16 near-tie noise.
        cfg = dataclasses.replace(LlamaConfig.tiny(),
                                  decode_attn="fused", dtype=jnp.float32)
        n_conv, p_len, turn_new = 6, 60, 8
        eng_kw = dict(n_slots=2, max_len=128, chunk=2, prefill_bucket=8,
                      page_size=8, n_pages=20)
        dram_pages = 64
    else:
        cfg = LlamaConfig(
            vocab=32000, d_model=1024, n_layers=4, n_heads=16,
            n_kv_heads=16, d_ff=4096, max_seq=4096, remat=False,
            decode_attn="fused")
        n_conv, p_len, turn_new = 16, 512, 64
        eng_kw = dict(n_slots=4, max_len=4096, chunk=16,
                      prefill_bucket=64, page_size=64, n_pages=48)
        dram_pages = 256
    # Corpus sizing invariant the leg depends on: every conversation's
    # cached pages together overflow the pool (turn 2 cannot be served
    # from residency) but fit the DRAM tier (nothing spills to disk).
    ps = eng_kw["page_size"]
    conv_pages = (p_len + turn_new) // ps
    assert n_conv * conv_pages > eng_kw["n_pages"], "corpus fits the pool"
    assert n_conv * conv_pages <= dram_pages, "corpus overflows the tier"
    params = init_params(cfg, jax.random.PRNGKey(0))

    def drive(tiering, guard=None):
        """Two turns over the same conversation corpus. Returns
        (replies per conv, engine, warm-pass metric snapshot, wall
        seconds, turn-1 request metrics, turn-2 request metrics)."""
        tier_kw = dict(kv_tiering=True, dram_pages=dram_pages) \
            if tiering else {}
        eng = ContinuousBatcher(params, cfg, kv_dtype="int8",
                                kv_layout="paged", prefix_cache=True,
                                **tier_kw, **eng_kw)
        # Warm pass: ONE extra conversation walks both turns' prefill
        # rungs (cold full-prompt bucket + hit-suffix bucket — resident
        # and promoted hits share the same page arithmetic, hence the
        # same compiled shapes) outside the measured window.
        wrng = np.random.default_rng(99)
        transcript = []
        for turn in range(2):
            prompt = transcript + list(
                wrng.integers(0, cfg.vocab, p_len if turn == 0
                              else turn_new))
            eng.submit(prompt, max_new=turn_new)
            done = {}
            while eng.pending:
                done.update(eng.step())
            (_, toks), = done.items()
            transcript = prompt + toks
        eng.pop_request_metrics()
        warm = eng.pool_metrics()
        if guard is not None:
            guard.track("decode", eng._decode)
            guard.track("prefill", eng._prefill)
            guard.snapshot()
        rngs = [np.random.default_rng(1000 + c) for c in range(n_conv)]
        prompts = [list(r.integers(0, cfg.vocab, p_len)) for r in rngs]
        replies = [[] for _ in range(n_conv)]
        met_by_turn = []
        t0 = time.perf_counter()
        for turn in range(2):
            rids = {}
            for c in range(n_conv):
                prompt = prompts[c] if turn == 0 else (
                    prompts[c] + replies[c][0]
                    + list(rngs[c].integers(0, cfg.vocab, turn_new)))
                rids[eng.submit(prompt, max_new=turn_new)] = c
            done = {}
            while eng.pending:
                done.update(eng.step())
            for rid, c in rids.items():
                replies[c].append(done[rid])
            met_by_turn.append(eng.pop_request_metrics())
        wall = time.perf_counter() - t0
        eng._alloc.assert_consistent()
        return replies, eng, warm, wall, met_by_turn[0], met_by_turn[1]

    guard = RecompileGuard()
    rep_on, eng_on, warm_on, wall_on, met_cold, met_warm = \
        drive(True, guard)
    retraces = sum(guard.misses_since().values())
    rep_off, eng_off, warm_off, wall_off, _, met_off2 = drive(False)
    identity = rep_on == rep_off

    m_on, m_off = eng_on.pool_metrics(), eng_off.pool_metrics()

    def window_hit_rate(m, warm):
        hits = m["prefix_lookup_hits"] - warm["prefix_lookup_hits"]
        lookups = m["prefix_lookups"] - warm["prefix_lookups"]
        return hits / lookups if lookups else 0.0

    promoted = sum(m_on.get("promoted_hit_token_batch") or ())
    total_tokens = n_conv * 2 * turn_new
    extra = {
        "kv_tiering_shape": f"{n_conv} convs x 2 turns (prompt {p_len}, "
                            f"{turn_new} new/turn), pool "
                            f"{eng_kw['n_pages']}p + dram {dram_pages}p",
        "kv_tiering_interpret": not on_tpu,
        "kv_tiering_token_identity": bool(identity),
        "kv_tiering_retraces": int(retraces),
        "kv_tiering_hit_rate_on": round(window_hit_rate(m_on, warm_on), 3),
        "kv_tiering_hit_rate_off": round(
            window_hit_rate(m_off, warm_off), 3),
        "kv_tiering_demotions": int(
            m_on["page_demotions_total"]
            - warm_on["page_demotions_total"]),
        "kv_tiering_promotions": int(
            m_on["page_promotions_total"]
            - warm_on["page_promotions_total"]),
        "kv_tiering_promoted_hit_tokens": int(promoted),
        "kv_tiering_dram_pages": int(m_on["tier_dram_pages"]),
        "kv_tiering_tok_s_on": round(total_tokens / wall_on, 1),
        "kv_tiering_tok_s_off": round(total_tokens / wall_off, 1),
    }
    extra.update(_latency_stats(met_warm, prefix="kv_tiering_warm_"))
    extra.update(_latency_stats(met_cold, prefix="kv_tiering_cold_"))
    extra.update(_latency_stats(met_off2, prefix="kv_tiering_off_turn2_"))
    return {
        "metric": "kv_tiering_bench",
        "value": extra["kv_tiering_warm_ttft_p50_ms"],
        "unit": "ms_warm_ttft_p50",
        "extra": extra,
    }


def bench_sharded_decode(smoke=False, tp=2):
    """Multi-chip sharded paged serving (shard_map islands over tp) on
    FORCED host devices: the same open-loop workload through an
    unsharded (tp=1) and a sharded (tp=N) paged engine, CI-asserting the
    whole contract — token identity (sharding must never change an
    answer), zero retrace across the measured steady-state pass with the
    pool + scales + table donated through the island, per-chip kv-pool
    resident bytes scaling 1/tp (the capacity headroom the feature
    exists for), and tok/s on both so the island's gather/communication
    overhead stays visible run over run. On real multi-chip hardware the
    same leg measures the actual scale-up; under the CPU backend the
    tok/s DELTA is emulation noise — only the invariants are asserted."""
    # Must land before the first jax backend init: host-platform device
    # virtualization is how the leg gets a multi-chip mesh in CI. APPEND
    # to a pre-set XLA_FLAGS rather than setdefault — a developer's
    # exported flags would otherwise leave 1 device and silently degrade
    # the leg to its error dict.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = ((flags + " ") if flags else "") + \
            f"--xla_force_host_platform_device_count={2 * tp}"
    import dataclasses

    import jax
    import numpy as np
    from jax.sharding import Mesh

    from k8s_gpu_scheduler_tpu.analysis.recompile import RecompileGuard
    from k8s_gpu_scheduler_tpu.models.llama import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

    if len(jax.devices()) < tp:
        return {"metric": "sharded_decode_tok_s", "value": 0.0,
                "unit": "tok/s",
                "extra": {"sharded_error":
                          f"need {tp} devices, have {len(jax.devices())}"}}
    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = dataclasses.replace(
        LlamaConfig.tiny() if not on_tpu or smoke else LlamaConfig(
            vocab=32000, d_model=1024, n_layers=4, n_heads=16,
            n_kv_heads=8, d_ff=2816, max_seq=2048, remat=False),
        decode_attn="fused")
    params = init_params(cfg, jax.random.PRNGKey(0))
    max_len, page = (64, 8) if not on_tpu or smoke else (1024, 64)
    n_req, max_new = (10, 8) if smoke else (24, 16)

    def build(mesh):
        return ContinuousBatcher(
            params, cfg, n_slots=4, max_len=max_len, chunk=4,
            prefill_bucket=2 * page, kv_dtype="int8", kv_layout="paged",
            page_size=page, mesh=mesh)

    def drive(eng, measure=False):
        rng = np.random.default_rng(7)
        t0 = time.perf_counter()
        out = {}
        guard = None
        for wave in range(3):
            for _ in range(n_req // 3):
                eng.submit(rng.integers(0, cfg.vocab, int(
                    rng.integers(page // 2, 3 * page))), max_new=max_new)
            out.update(eng.run())
            if measure and wave == 0 and guard is None:
                # Wave 0 is the warmup (both block-table jit keys + the
                # lens/last committal); waves 1-2 are the measured
                # steady state.
                guard = RecompileGuard()
                guard.track("decode", eng._decode)
                guard.track("prefill", eng._prefill)
                guard.snapshot()
        wall = time.perf_counter() - t0
        toks = sum(len(v) for v in out.values())
        misses = guard.misses_since() if guard else {}
        return out, toks / wall, misses

    e1 = build(None)
    ref, tok_s_1, _ = drive(e1)
    bytes_1 = e1.pool_metrics()["kv_pool_device_bytes"]

    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))
    e2 = build(mesh)
    got, tok_s_tp, misses = drive(e2, measure=True)
    pm = e2.pool_metrics()
    bytes_tp = pm["kv_pool_device_bytes"]

    pm1 = e1.pool_metrics()
    extra = {
        "sharded_interpret": not on_tpu,
        "sharded_tp": tp,
        "sharded_token_identity": got == ref,
        "sharded_zero_retrace": not any(misses.values()),
        "sharded_retraces": {k: int(v) for k, v in misses.items()},
        "sharded_pool_bytes_tp1": int(bytes_1),
        "sharded_pool_bytes_per_chip": int(bytes_tp),
        # Exact 1/tp: the pool shards on the kv-heads dim with no
        # padding (Hkv % tp == 0 is an admission-time invariant).
        "sharded_pool_bytes_scaled": int(bytes_tp) * tp == int(bytes_1),
        "sharded_tok_s_tp1": round(tok_s_1, 1),
        f"sharded_tok_s_tp{tp}": round(tok_s_tp, 1),
        # Megatron-sliced weights (PR 15, the sharded_weights leg's
        # deep-dive rows folded into this table): the tp engine above
        # runs the weight-sharded default, so its per-chip weight
        # residency rides here too — the sliced subset is exactly 1/tp.
        "sharded_weight_bytes_per_chip": int(pm["weight_device_bytes"]),
        "sharded_weight_sliced_scaled":
            int(pm["weight_sliced_device_bytes"]) * tp
            == int(pm1["weight_sliced_device_bytes"]),
        "sharded_tp_combine": pm["tp_combine"],
    }
    return {
        "metric": "sharded_decode_tok_s",
        "value": round(tok_s_tp, 1),
        "unit": "tok/s",
        "extra": extra,
    }


def bench_sharded_weights(smoke=False, tp=2):
    """Megatron-sliced weights through the tp islands (PR 15) on FORCED
    host devices: the same open-loop workload through four engines —
    unsharded (tp=1), weight-sharded tp=N with the all_gather combine
    (the default: movement-only, byte-identical), weight-sharded tp=N
    with the psum combine (1/tp row-matmul FLOPs, tolerance-checked),
    and the LEGACY replicated-weight island (weight_sharding=False) —
    CI-asserting the whole contract: all_gather streams byte-identical
    to tp=1 AND to the replicated island, per-chip bytes of the
    WEIGHT_SPECS-sliced weight leaves exactly 1/tp, total per-chip
    weight residency strictly below replicated, zero retrace across the
    measured steady state with pool + scales + table donated, and tok/s
    for every engine so the combine overhead stays visible run over
    run. On CPU the tok/s deltas are emulation noise — only the
    invariants are asserted."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = ((flags + " ") if flags else "") + \
            f"--xla_force_host_platform_device_count={2 * tp}"
    import dataclasses
    import warnings

    import jax
    import numpy as np
    from jax.sharding import Mesh

    from k8s_gpu_scheduler_tpu.analysis.recompile import RecompileGuard
    from k8s_gpu_scheduler_tpu.models.llama import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

    if len(jax.devices()) < tp:
        return {"metric": "sharded_weights_tok_s", "value": 0.0,
                "unit": "tok/s",
                "extra": {"wsharded_error":
                          f"need {tp} devices, have {len(jax.devices())}"}}
    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = dataclasses.replace(
        LlamaConfig.tiny() if not on_tpu or smoke else LlamaConfig(
            vocab=32000, d_model=1024, n_layers=4, n_heads=16,
            n_kv_heads=8, d_ff=2816, max_seq=2048, remat=False),
        decode_attn="fused")
    params = init_params(cfg, jax.random.PRNGKey(0))
    max_len, page = (64, 8) if not on_tpu or smoke else (1024, 64)
    n_req, max_new = (10, 8) if smoke else (24, 16)

    def build(mesh, **kw):
        return ContinuousBatcher(
            params, cfg, n_slots=4, max_len=max_len, chunk=4,
            prefill_bucket=2 * page, kv_dtype="int8", kv_layout="paged",
            page_size=page, mesh=mesh, **kw)

    def drive(eng, measure=False):
        rng = np.random.default_rng(7)
        t0 = time.perf_counter()
        out = {}
        guard = None
        for wave in range(3):
            for _ in range(n_req // 3):
                eng.submit(rng.integers(0, cfg.vocab, int(
                    rng.integers(page // 2, 3 * page))), max_new=max_new)
            out.update(eng.run())
            if measure and wave == 0 and guard is None:
                guard = RecompileGuard()
                guard.track("decode", eng._decode)
                guard.track("prefill", eng._prefill)
                guard.snapshot()
        wall = time.perf_counter() - t0
        toks = sum(len(v) for v in out.values())
        misses = guard.misses_since() if guard else {}
        return out, toks / wall, misses

    e1 = build(None)
    ref, tok_s_1, _ = drive(e1)
    pm1 = e1.pool_metrics()

    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))
    eag = build(mesh)                                # the default
    got_ag, tok_s_ag, misses = drive(eag, measure=True)
    pm_ag = eag.pool_metrics()

    eps_ = build(mesh, tp_combine="psum")
    got_ps, tok_s_ps, _ = drive(eps_)
    pm_ps = eps_.pool_metrics()

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        erep = build(mesh, weight_sharding=False)
    got_rep, tok_s_rep, _ = drive(erep)
    pm_rep = erep.pool_metrics()

    extra = {
        "wsharded_interpret": not on_tpu,
        "wsharded_tp": tp,
        # all_gather: byte-pinned against BOTH references.
        "wsharded_token_identity": got_ag == ref,
        "wsharded_identity_vs_replicated": got_ag == got_rep,
        # psum: tolerance-checked by contract, NOT byte-pinned — a
        # logit near-tie can flip an argmax under the changed reduction
        # order, so the identity bit is a REPORTED fact while CI
        # asserts the agreement FLOOR (near-ties are rare: ≥ 0.8 of
        # streams byte-match on any trace; the numeric bound itself is
        # test-pinned in test_sharded_serving).
        "wsharded_psum_token_identity": got_ps == ref,
        "wsharded_psum_stream_agreement": round(
            sum(got_ps[r] == ref[r] for r in ref) / max(1, len(ref)), 3),
        "wsharded_zero_retrace": not any(misses.values()),
        "wsharded_retraces": {k: int(v) for k, v in misses.items()},
        "wsharded_sliced_bytes_tp1":
            int(pm1["weight_sliced_device_bytes"]),
        "wsharded_sliced_bytes_per_chip":
            int(pm_ag["weight_sliced_device_bytes"]),
        # Exact 1/tp on the WEIGHT_SPECS-sliced subset (no padding —
        # divisibility is an __init__ invariant); total per-chip
        # residency strictly below the replicated island's.
        "wsharded_sliced_bytes_scaled":
            int(pm_ag["weight_sliced_device_bytes"]) * tp
            == int(pm1["weight_sliced_device_bytes"]),
        "wsharded_total_bytes_per_chip": int(pm_ag["weight_device_bytes"]),
        "wsharded_total_below_replicated":
            pm_ag["weight_device_bytes"] < pm_rep["weight_device_bytes"],
        "wsharded_psum_bytes_match":
            pm_ps["weight_device_bytes"] == pm_ag["weight_device_bytes"],
        "wsharded_combines": [pm_ag["tp_combine"], pm_ps["tp_combine"],
                              pm_rep["tp_combine"]],
        "wsharded_tok_s_tp1": round(tok_s_1, 1),
        f"wsharded_tok_s_tp{tp}_all_gather": round(tok_s_ag, 1),
        f"wsharded_tok_s_tp{tp}_psum": round(tok_s_ps, 1),
        f"wsharded_tok_s_tp{tp}_replicated": round(tok_s_rep, 1),
    }
    return {
        "metric": "sharded_weights_tok_s",
        "value": round(tok_s_ag, 1),
        "unit": "tok/s",
        "extra": extra,
    }


def main(argv=None):
    args = list(sys.argv[1:] if argv is None else argv)
    if "--leg" in args:
        # Single-leg mode: one JSON line for the named leg only (used by
        # the decode-attention smoke test and for kernel iteration without
        # paying the full scheduler/train/serve line).
        idx = args.index("--leg") + 1
        leg = args[idx] if idx < len(args) else None
        if leg == "decode_attention":
            print(json.dumps(bench_decode_attention(
                smoke="--smoke" in args)))
            return
        if leg == "paged_attention":
            print(json.dumps(bench_paged_attention(
                smoke="--smoke" in args)))
            return
        if leg == "prefix_cache":
            print(json.dumps(bench_prefix_cache(smoke="--smoke" in args)))
            return
        if leg == "speculative":
            print(json.dumps(bench_speculative(smoke="--smoke" in args)))
            return
        if leg == "analysis":
            print(json.dumps(bench_analysis(smoke="--smoke" in args)))
            return
        if leg == "chaos":
            print(json.dumps(bench_chaos(smoke="--smoke" in args)))
            return
        if leg == "obs_overhead":
            print(json.dumps(bench_obs_overhead(smoke="--smoke" in args)))
            return
        if leg == "fleet":
            print(json.dumps(bench_fleet(smoke="--smoke" in args)))
            return
        if leg == "fleet_chaos":
            print(json.dumps(bench_fleet_chaos(smoke="--smoke" in args)))
            return
        if leg == "chunked_prefill":
            print(json.dumps(bench_chunked_prefill(smoke="--smoke" in args)))
            return
        if leg == "disagg":
            print(json.dumps(bench_disagg(smoke="--smoke" in args)))
            return
        if leg == "sharded_decode":
            print(json.dumps(bench_sharded_decode(smoke="--smoke" in args)))
            return
        if leg == "sharded_weights":
            print(json.dumps(bench_sharded_weights(smoke="--smoke" in args)))
            return
        if leg == "multiturn":
            print(json.dumps(bench_multiturn(smoke="--smoke" in args)))
            return
        if leg == "kv_tiering":
            print(json.dumps(bench_kv_tiering(smoke="--smoke" in args)))
            return
        raise SystemExit(f"unknown bench leg: {leg!r} (available: "
                         f"decode_attention, paged_attention, prefix_cache, "
                         f"speculative, analysis, chaos, obs_overhead, "
                         f"fleet, fleet_chaos, chunked_prefill, disagg, "
                         f"sharded_decode, sharded_weights, multiturn, "
                         f"kv_tiering)")
    # Same process-level GIL tuning as the cmd/scheduler.py entrypoint —
    # the bench measures the scheduler as deployed.
    sys.setswitchinterval(0.001)
    # Discarded warmup: the first churn pays one-time costs (module
    # bytecode, thread-pool spin-up, allocator warm) that would otherwise
    # land in the measured leg's p50.
    try:
        bench_schedule_churn(n_nodes=4, n_pods=8)
    except Exception:  # noqa: BLE001
        pass
    # Headline leg is MEDIAN-of-3 by p50: sub-2ms medians are at the mercy
    # of GC pauses and background threads. The median is noise-robust
    # without biasing the headline favorably (min-of-N would), and every
    # trial's p50 is emitted so run-to-run variance stays visible.
    trials = [bench_schedule_churn()]
    for _ in range(2):
        try:
            trials.append(bench_schedule_churn())
        except Exception:  # noqa: BLE001
            break
    run_order = [t["p50_ms"] for t in trials]        # before sorting: drift visible
    trials.sort(key=lambda t: t["p50_ms"])
    churn = dict(trials[len(trials) // 2])
    churn["p50_trials_ms"] = run_order
    try:
        churn_rest = bench_schedule_churn(rest=True)
    except Exception as e:  # noqa: BLE001 — REST leg must not kill the line
        churn_rest = {"rest_error": str(e)[:200]}
    try:
        # Scale leg (VERDICT r3 #5): 256 nodes / 512 pods over REST —
        # exercises the parallel Filter fan-out + feasible-node sampling.
        churn_256 = bench_schedule_churn(
            n_nodes=256, n_pods=512, rest=True, suffix="_rest256")
    except Exception as e:  # noqa: BLE001
        churn_256 = {"rest256_error": str(e)[:200]}
    try:
        # Adversarial mixed load at 1024 nodes (VERDICT r4 #5).
        mixed = bench_mixed()
    except Exception as e:  # noqa: BLE001
        mixed = {"mixed1024_error": str(e)[:200]}
    try:
        train = bench_train_mfu()
    except Exception as e:  # noqa: BLE001 — accelerator part must not kill the line
        train = {"error": str(e)[:200]}
    try:
        serve = bench_serving()
    except Exception as e:  # noqa: BLE001
        serve = {"serve_error": str(e)[:200]}
    try:
        # Fast passes only in the headline line (the dedicated
        # `--leg analysis` records the traced passes too): lint latency is
        # tracked so it can't quietly become a CI tax.
        analysis = bench_analysis(smoke=True)["extra"]
    except Exception as e:  # noqa: BLE001
        analysis = {"analysis_error": str(e)[:200]}
    p50 = churn["p50_ms"] or 1e-6
    print(json.dumps({
        "metric": "p50_schedule_latency_64pod_churn",
        "value": churn["p50_ms"],
        "unit": "ms",
        "vs_baseline": round(BASELINE_P50_MS / p50, 2),
        "extra": {**churn, **churn_rest, **churn_256, **mixed, **train,
                  **serve, **analysis},
    }))


if __name__ == "__main__":
    main()
