// kvstored — TPU-inventory KV registry speaking RESP2.
//
// The reference parks its GPU-UUID registry in a stock Redis StatefulSet
// (deploy/redis/, NodePort 32767, requirepass — SURVEY.md §2 C20) and talks
// to it via go-redis (pkg/redis/client/client.go:26-67: Set/Get/GetRange/
// GetKeys/FlushRedis). Our registry is this single-binary C++ server: the
// repo's native-component obligation (SURVEY.md §2 native checklist — the
// reference's only C++, pkg/profiler/gpu_profiling.cpp, is dead code). It
// speaks enough RESP that any redis client can drive it:
//
//   PING AUTH SELECT SET GET GETRANGE DEL EXISTS KEYS DBSIZE
//   FLUSHDB FLUSHALL QUIT COMMAND INFO
//
// plus append-only persistence (--appendonly FILE replays a RESP command log
// at startup — parity with the reference's Redis AOF-on-PV durability,
// SURVEY.md §5 "Checkpoint / resume"). AOF hygiene mirrors Redis:
// --appendfsync always|everysec|no (default everysec — at most one second
// of acknowledged writes lost on power cut), the log is COMPACTED into a
// one-SET-per-live-key snapshot at startup (heartbeat rewrites otherwise
// grow it without bound and every restart replays all of it), and it
// auto-rewrites whenever it doubles past the last compaction.
//
// Concurrency: thread-per-connection; one mutex over the 16-db store. The
// write rate is node-agent inventory publishes (one key per node every few
// seconds) — contention is not a concern; simplicity and auditability are.
//
// Build: make (g++ -std=c++17 -O2 -pthread). No dependencies.

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>

#include <algorithm>
#include <array>
#include <cctype>
#include <cerrno>
#include <cstring>
#include <fstream>
#include <iostream>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kNumDbs = 16;

enum class Fsync { kAlways, kEverysec, kNo };

struct Store {
  std::mutex mu;
  std::array<std::unordered_map<std::string, std::string>, kNumDbs> dbs;
  int aof_fd = -1;
  bool aof_enabled = false;
  std::string aof_path;
  Fsync fsync_policy = Fsync::kEverysec;
  bool aof_dirty = false;        // bytes written since last fsync
  size_t aof_size = 0;           // bytes in the log now
  size_t aof_base_size = 0;      // bytes right after the last rewrite
};

Store g_store;
std::string g_password;  // empty = no auth required

// --- RESP writing -----------------------------------------------------------

std::string simple(const std::string& s) { return "+" + s + "\r\n"; }
std::string err(const std::string& s) { return "-ERR " + s + "\r\n"; }
std::string integer(long long n) { return ":" + std::to_string(n) + "\r\n"; }
std::string bulk(const std::string& s) {
  return "$" + std::to_string(s.size()) + "\r\n" + s + "\r\n";
}
std::string null_bulk() { return "$-1\r\n"; }
std::string array_hdr(size_t n) { return "*" + std::to_string(n) + "\r\n"; }

// --- glob matching for KEYS (supports * ? [abc]) ----------------------------

bool glob_match(const char* pat, const char* str) {
  while (*pat) {
    switch (*pat) {
      case '*': {
        pat++;
        if (!*pat) return true;
        for (const char* s = str; ; s++) {
          if (glob_match(pat, s)) return true;
          if (!*s) return false;
        }
      }
      case '?':
        if (!*str) return false;
        pat++, str++;
        break;
      case '[': {
        if (!*str) return false;
        const char* p = pat + 1;
        bool neg = (*p == '^');
        if (neg) p++;
        bool matched = false;
        while (*p && *p != ']') {
          if (p[1] == '-' && p[2] && p[2] != ']') {
            if (*str >= *p && *str <= p[2]) matched = true;
            p += 3;
          } else {
            if (*p == *str) matched = true;
            p++;
          }
        }
        if (*p != ']') return false;
        if (matched == neg) return false;
        pat = p + 1;
        str++;
        break;
      }
      default:
        if (*pat != *str) return false;
        pat++, str++;
    }
  }
  return !*str;
}

// --- AOF --------------------------------------------------------------------

std::mutex g_aof_mu;

std::string aof_frame(int db, const std::vector<std::string>& argv) {
  // Each record: db index, then the command, RESP-framed.
  std::string out = "#" + std::to_string(db) + "\r\n" + array_hdr(argv.size());
  for (const auto& a : argv) out += bulk(a);
  return out;
}

bool write_all(int fd, const std::string& buf) {
  size_t off = 0;
  while (off < buf.size()) {
    ssize_t n = write(fd, buf.data() + off, buf.size() - off);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    off += static_cast<size_t>(n);
  }
  return true;
}

// Compacts the log to one SET per live key — the state the replay would
// rebuild, minus every superseded heartbeat write. Caller must hold
// g_store.mu (reads the dbs) and g_aof_mu (swaps the fd); at startup,
// before any client thread exists, neither is needed.
bool aof_rewrite_locked() {
  const std::string tmp = g_store.aof_path + ".rewrite";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return false;
  std::string buf;
  for (int db = 0; db < kNumDbs; db++) {
    for (const auto& kv : g_store.dbs[db]) {
      buf += aof_frame(db, {"SET", kv.first, kv.second});
      if (buf.size() > (1u << 20)) {
        if (!write_all(fd, buf)) { close(fd); return false; }
        buf.clear();
      }
    }
  }
  size_t total_hint = 0;
  if (!buf.empty() && !write_all(fd, buf)) { close(fd); return false; }
  // fsync BEFORE rename: the rename must never expose a file whose data
  // is still only in the page cache.
  if (fsync(fd) != 0) { close(fd); return false; }
  off_t sz = lseek(fd, 0, SEEK_END);
  total_hint = sz > 0 ? static_cast<size_t>(sz) : 0;
  close(fd);
  if (rename(tmp.c_str(), g_store.aof_path.c_str()) != 0) return false;
  if (g_store.aof_fd >= 0) close(g_store.aof_fd);
  g_store.aof_fd = open(g_store.aof_path.c_str(),
                        O_WRONLY | O_APPEND, 0644);
  g_store.aof_size = g_store.aof_base_size = total_hint;
  g_store.aof_dirty = false;
  return g_store.aof_fd >= 0;
}

void aof_record(int db, const std::vector<std::string>& argv) {
  if (!g_store.aof_enabled) return;
  std::lock_guard<std::mutex> lk(g_aof_mu);
  const std::string rec = aof_frame(db, argv);
  if (!write_all(g_store.aof_fd, rec)) {
    // FAIL-STOP: a partial frame (ENOSPC/EIO) is a torn record; appending
    // more after it would bury every later write behind the point where
    // replay stops. Disable persistence loudly instead — replay then
    // loses only this one record.
    std::cerr << "kvstored: AOF append failed (" << std::strerror(errno)
              << "); persistence DISABLED\n";
    g_store.aof_enabled = false;
    return;
  }
  g_store.aof_size += rec.size();
  if (g_store.fsync_policy == Fsync::kAlways) {
    fsync(g_store.aof_fd);
  } else {
    g_store.aof_dirty = true;
  }
  // Auto-rewrite once the log doubles past the last compaction (Redis's
  // auto-aof-rewrite-percentage 100) with a 1 MiB floor; the caller
  // already holds g_store.mu (every aof_record call site is inside
  // execute()'s store critical section), so the rewrite may read the dbs.
  // The rewrite is SYNCHRONOUS under both locks — deliberate: the store
  // is node-inventory scale (KBs per node), so the stall is bounded by a
  // few MBs of sequential IO; Redis forks for this because its stores are
  // GBs. Revisit if the registry ever holds more than inventory.
  if (g_store.aof_size > (1u << 20) &&
      g_store.aof_size > 2 * std::max<size_t>(g_store.aof_base_size, 1)) {
    if (!aof_rewrite_locked()) {
      std::cerr << "kvstored: AOF auto-rewrite failed; persistence "
                   "DISABLED\n";
      g_store.aof_enabled = false;
    }
  }
}

// everysec fsync pump — at most one second of acknowledged writes is lost
// on power cut (Redis's appendfsync everysec contract).
void fsync_loop() {
  for (;;) {
    std::this_thread::sleep_for(std::chrono::seconds(1));
    std::lock_guard<std::mutex> lk(g_aof_mu);
    if (g_store.aof_dirty && g_store.aof_fd >= 0) {
      fsync(g_store.aof_fd);
      g_store.aof_dirty = false;
    }
  }
}

// --- command dispatch -------------------------------------------------------

std::string upper(std::string s) {
  std::transform(s.begin(), s.end(), s.begin(),
                 [](unsigned char c) { return std::toupper(c); });
  return s;
}

struct Session {
  bool authed = g_password.empty();
  int db = 0;
};

// Applies a (possibly replayed) command against the store. Returns the RESP
// response. `record` controls AOF logging (false during replay).
std::string execute(Session& sess, const std::vector<std::string>& argv, bool record) {
  if (argv.empty()) return err("empty command");
  const std::string cmd = upper(argv[0]);

  if (cmd == "QUIT") return simple("OK");
  if (cmd == "AUTH") {
    if (argv.size() != 2) return err("wrong number of arguments for 'auth'");
    if (g_password.empty()) return err("Client sent AUTH, but no password is set");
    if (argv[1] == g_password) {
      sess.authed = true;
      return simple("OK");
    }
    return err("invalid password");
  }
  if (!sess.authed) return "-NOAUTH Authentication required.\r\n";

  if (cmd == "PING") return simple(argv.size() > 1 ? argv[1] : "PONG");
  if (cmd == "COMMAND") return array_hdr(0);
  if (cmd == "INFO") return bulk("# kvstored\r\nrole:master\r\n");
  if (cmd == "SELECT") {
    if (argv.size() != 2) return err("wrong number of arguments for 'select'");
    int n = -1;
    try {
      n = std::stoi(argv[1]);
    } catch (...) {
    }
    if (n < 0 || n >= kNumDbs) return err("DB index is out of range");
    sess.db = n;
    return simple("OK");
  }

  std::lock_guard<std::mutex> lk(g_store.mu);
  auto& db = g_store.dbs[sess.db];

  if (cmd == "SET") {
    if (argv.size() != 3) return err("wrong number of arguments for 'set'");
    db[argv[1]] = argv[2];
    if (record) aof_record(sess.db, argv);
    return simple("OK");
  }
  if (cmd == "GET") {
    if (argv.size() != 2) return err("wrong number of arguments for 'get'");
    auto it = db.find(argv[1]);
    return it == db.end() ? null_bulk() : bulk(it->second);
  }
  if (cmd == "MGET") {
    // Redis MGET: one array reply, nil per missing key — lets
    // list_inventories fetch a fleet in 2 round trips instead of N+1.
    if (argv.size() < 2) return err("wrong number of arguments for 'mget'");
    std::string out = array_hdr(argv.size() - 1);
    for (size_t i = 1; i < argv.size(); i++) {
      auto it = db.find(argv[i]);
      out += it == db.end() ? null_bulk() : bulk(it->second);
    }
    return out;
  }
  if (cmd == "GETRANGE") {
    // Parity with client.Descriptor.GetRange (client.go:36-40).
    if (argv.size() != 4) return err("wrong number of arguments for 'getrange'");
    auto it = db.find(argv[1]);
    if (it == db.end()) return bulk("");
    long long start = 0, end = -1;
    try {
      start = std::stoll(argv[2]);
      end = std::stoll(argv[3]);
    } catch (...) {
      return err("value is not an integer or out of range");
    }
    long long len = static_cast<long long>(it->second.size());
    if (start < 0) start = std::max(0LL, len + start);
    if (end < 0) end = len + end;
    end = std::min(end, len - 1);
    if (start > end || len == 0) return bulk("");
    return bulk(it->second.substr(start, end - start + 1));
  }
  if (cmd == "DEL") {
    if (argv.size() < 2) return err("wrong number of arguments for 'del'");
    long long removed = 0;
    for (size_t i = 1; i < argv.size(); i++) removed += db.erase(argv[i]);
    if (record && removed) aof_record(sess.db, argv);
    return integer(removed);
  }
  if (cmd == "EXISTS") {
    if (argv.size() < 2) return err("wrong number of arguments for 'exists'");
    long long n = 0;
    for (size_t i = 1; i < argv.size(); i++) n += db.count(argv[i]);
    return integer(n);
  }
  if (cmd == "KEYS") {
    // Parity with client.Descriptor.GetKeys (client.go:42-46).
    if (argv.size() != 2) return err("wrong number of arguments for 'keys'");
    std::vector<const std::string*> hits;
    for (const auto& kv : db)
      if (glob_match(argv[1].c_str(), kv.first.c_str())) hits.push_back(&kv.first);
    std::string out = array_hdr(hits.size());
    for (const auto* k : hits) out += bulk(*k);
    return out;
  }
  if (cmd == "DBSIZE") return integer(static_cast<long long>(db.size()));
  if (cmd == "FLUSHDB") {
    // Parity with client.Descriptor.FlushRedis (client.go:48-52).
    db.clear();
    if (record) aof_record(sess.db, argv);
    return simple("OK");
  }
  if (cmd == "FLUSHALL") {
    for (auto& d : g_store.dbs) d.clear();
    if (record) aof_record(sess.db, argv);
    return simple("OK");
  }
  return err("unknown command '" + argv[0] + "'");
}

// --- RESP reading -----------------------------------------------------------

class Reader {
 public:
  explicit Reader(int fd) : fd_(fd) {}

  // Reads one command: RESP array of bulk strings, or an inline command.
  // Returns false on EOF/protocol error.
  bool next(std::vector<std::string>& argv) {
    argv.clear();
    std::string line;
    if (!read_line(line)) return false;
    if (line.empty()) return next(argv);
    if (line[0] == '*') {
      long long n = 0;
      try {
        n = std::stoll(line.substr(1));
      } catch (...) {
        return false;
      }
      if (n < 0 || n > 1024) return false;
      for (long long i = 0; i < n; i++) {
        std::string hdr;
        if (!read_line(hdr) || hdr.empty() || hdr[0] != '$') return false;
        long long len = 0;
        try {
          len = std::stoll(hdr.substr(1));
        } catch (...) {
          return false;
        }
        if (len < 0 || len > (64LL << 20)) return false;
        std::string payload;
        if (!read_exact(payload, static_cast<size_t>(len) + 2)) return false;
        payload.resize(len);  // strip trailing \r\n
        argv.push_back(std::move(payload));
      }
      return true;
    }
    // Inline command (telnet/netcat convenience — redis supports this too).
    std::istringstream ss(line);
    std::string tok;
    while (ss >> tok) argv.push_back(tok);
    return !argv.empty();
  }

 private:
  bool fill() {
    char buf[4096];
    ssize_t n = recv(fd_, buf, sizeof(buf), 0);
    if (n <= 0) return false;
    buf_.append(buf, n);
    return true;
  }

  bool read_line(std::string& out) {
    size_t pos;
    while ((pos = buf_.find("\r\n")) == std::string::npos) {
      if (buf_.size() > (64u << 20)) return false;
      if (!fill()) return false;
    }
    out = buf_.substr(0, pos);
    buf_.erase(0, pos + 2);
    return true;
  }

  bool read_exact(std::string& out, size_t n) {
    while (buf_.size() < n)
      if (!fill()) return false;
    out = buf_.substr(0, n);
    buf_.erase(0, n);
    return true;
  }

  int fd_;
  std::string buf_;
};

bool send_all(int fd, const char* data, size_t len) {
  // POSIX allows short counts from blocking send (large replies, EINTR) —
  // a single send would silently truncate and desync the RESP stream.
  size_t off = 0;
  while (off < len) {
    ssize_t n = send(fd, data + off, len - off, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    off += static_cast<size_t>(n);
  }
  return true;
}

void serve_client(int fd) {
  Session sess;
  Reader reader(fd);
  std::vector<std::string> argv;
  while (reader.next(argv)) {
    std::string resp = execute(sess, argv, /*record=*/true);
    if (!send_all(fd, resp.data(), resp.size())) break;
    if (!argv.empty() && upper(argv[0]) == "QUIT") break;
  }
  close(fd);
}

// --- AOF replay -------------------------------------------------------------

// Returns true when the whole file parsed (or it doesn't exist); false
// means a torn/corrupt tail was skipped — main() preserves the original
// bytes for manual recovery before compacting over them.
bool replay_aof(const std::string& path) {
  std::ifstream in(path, std::ios::binary);
  if (!in) return true;
  std::string content((std::istreambuf_iterator<char>(in)),
                      std::istreambuf_iterator<char>());
  size_t pos = 0;
  Session sess;
  sess.authed = true;
  auto read_line = [&](std::string& out) -> bool {
    size_t e = content.find("\r\n", pos);
    if (e == std::string::npos) return false;
    out = content.substr(pos, e - pos);
    pos = e + 2;
    return true;
  };
  // A crash mid-aof_record leaves a truncated tail; replay applies every
  // complete record and stops at the first malformed one instead of
  // crashing startup or indexing out of range.
  std::string line;
  while (read_line(line)) {
    if (line.empty() || line[0] != '#') continue;
    int db = -1;
    try {
      db = std::stoi(line.substr(1));
    } catch (...) {
      break;
    }
    if (db < 0 || db >= kNumDbs) break;
    sess.db = db;
    std::string hdr;
    if (!read_line(hdr) || hdr.empty() || hdr[0] != '*') break;
    long long n = 0;
    try {
      n = std::stoll(hdr.substr(1));
    } catch (...) {
      break;
    }
    if (n <= 0 || n > 1024) break;
    std::vector<std::string> argv;
    bool ok = true;
    for (long long i = 0; i < n && ok; i++) {
      std::string bh;
      ok = read_line(bh) && !bh.empty() && bh[0] == '$';
      if (!ok) break;
      long long len = -1;
      try {
        len = std::stoll(bh.substr(1));
      } catch (...) {
        ok = false;
        break;
      }
      if (len < 0 || pos + len + 2 > content.size()) {
        ok = false;
        break;
      }
      argv.push_back(content.substr(pos, len));
      pos += len + 2;
    }
    if (!ok) break;
    execute(sess, argv, /*record=*/false);
  }
  return pos >= content.size();
}

}  // namespace

int main(int argc, char** argv) {
  int port = 32767;
  std::string aof_path;
  // Loopback by default: an unauthenticated store must not appear on all
  // interfaces just because someone ran the binary bare. Deploy manifests
  // pass --bind 0.0.0.0 together with --requirepass.
  std::string bind_addr = "127.0.0.1";
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    if (a == "--port" && i + 1 < argc) port = std::stoi(argv[++i]);
    else if (a == "--bind" && i + 1 < argc) bind_addr = argv[++i];
    else if (a == "--requirepass" && i + 1 < argc) g_password = argv[++i];
    else if (a == "--appendonly" && i + 1 < argc) aof_path = argv[++i];
    else if (a == "--appendfsync" && i + 1 < argc) {
      std::string p = argv[++i];
      if (p == "always") g_store.fsync_policy = Fsync::kAlways;
      else if (p == "everysec") g_store.fsync_policy = Fsync::kEverysec;
      else if (p == "no") g_store.fsync_policy = Fsync::kNo;
      else {
        std::cerr << "bad --appendfsync (always|everysec|no)\n";
        return 1;
      }
    }
    else if (a == "--help") {
      std::cout << "kvstored [--port N] [--bind ADDR] [--requirepass PW] "
                   "[--appendonly FILE] [--appendfsync always|everysec|no]\n";
      return 0;
    }
  }

  if (!aof_path.empty()) {
    if (!replay_aof(aof_path)) {
      // Torn/corrupt tail: the compaction below would destroy the bytes
      // after the tear — keep them for manual recovery first.
      const std::string save = aof_path + ".corrupt";
      std::cerr << "kvstored: AOF has a corrupt tail; preserving original "
                   "as " << save << "\n";
      std::ifstream src(aof_path, std::ios::binary);
      std::ofstream dst(save, std::ios::binary | std::ios::trunc);
      dst << src.rdbuf();
    }
    // Startup compaction: replace the replayed history with a snapshot of
    // the state it produced (single-threaded here, no locks needed). The
    // pre-rewrite log is a heartbeat-per-node append stream — unbounded
    // growth, fully replayed on every restart without this.
    g_store.aof_path = aof_path;
    g_store.aof_enabled = aof_rewrite_locked();
    if (!g_store.aof_enabled) {
      std::cerr << "appendonly rewrite/open failed for " << aof_path << "\n";
      return 1;
    }
    if (g_store.fsync_policy == Fsync::kEverysec) {
      std::thread(fsync_loop).detach();
    }
  }

  signal(SIGPIPE, SIG_IGN);

  int listener = socket(AF_INET, SOCK_STREAM, 0);
  if (listener < 0) {
    perror("socket");
    return 1;
  }
  int one = 1;
  setsockopt(listener, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (inet_pton(AF_INET, bind_addr.c_str(), &addr.sin_addr) != 1) {
    std::cerr << "bad --bind address: " << bind_addr << "\n";
    return 1;
  }
  if (bind_addr != "127.0.0.1" && g_password.empty()) {
    std::cerr << "refusing non-loopback --bind without --requirepass\n";
    return 1;
  }
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(listener, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    perror("bind");
    return 1;
  }
  if (listen(listener, 128) < 0) {
    perror("listen");
    return 1;
  }
  // If --port 0, report the kernel-assigned port so tests can connect.
  socklen_t alen = sizeof(addr);
  getsockname(listener, reinterpret_cast<sockaddr*>(&addr), &alen);
  std::cout << "kvstored ready on port " << ntohs(addr.sin_port) << std::endl;

  while (true) {
    int fd = accept(listener, nullptr, nullptr);
    if (fd < 0) continue;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::thread(serve_client, fd).detach();
  }
}
