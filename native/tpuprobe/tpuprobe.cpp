// tpuprobe — native TPU inventory/utilization prober.
//
// The REAL equivalent of the reference's vestigial CUDA probe
// (pkg/profiler/gpu_profiling.cpp:10-23 — free/total memory + SM count,
// never built: pkg/profiler/Makefile:13-14). This one is built and used:
// the node agent (k8s_gpu_scheduler_tpu/agent) execs it the way the
// reference's DaemonSet execs nvidia-smi (profile_gpu.sh:3-13,
// parse_smi_uuids.py:6), and parses one JSON object per probe from stdout.
//
// Probe sources, in order:
//   1. --fake FILE / TPUPROBE_FAKE: a JSON metrics file — the fake-libtpu
//      test seam (SURVEY.md hard part f: buildable + testable without TPU
//      hardware). The file is passed through after validation.
//   2. /dev/accel* (or TPUPROBE_DEV_GLOB): the accelerator device nodes a
//      GKE TPU VM exposes; one chip per node, utilization unknown (0) —
//      live duty cycle comes from the metrics layer, not the prober.
//
// Output schema (one line):
//   {"chips":[{"device_id":N,"duty_cycle":F,"hbm_used":N,"hbm_total":N}]}
#include <cctype>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <glob.h>
#include <string>
#include <unistd.h>
#include <vector>

namespace {

struct Chip {
  int device_id = 0;
  double duty_cycle = 0.0;
  long long hbm_used = 0;
  long long hbm_total = 0;
};

void emit(const std::vector<Chip>& chips) {
  std::string out = "{\"chips\":[";
  char buf[160];
  for (size_t i = 0; i < chips.size(); ++i) {
    const Chip& c = chips[i];
    snprintf(buf, sizeof buf,
             "%s{\"device_id\":%d,\"duty_cycle\":%.4f,\"hbm_used\":%lld,"
             "\"hbm_total\":%lld}",
             i ? "," : "", c.device_id, c.duty_cycle, c.hbm_used, c.hbm_total);
    out += buf;
  }
  out += "]}\n";
  fputs(out.c_str(), stdout);
  fflush(stdout);
}

// Minimal field scanner for the fake file: pulls every {...} object's
// device_id/duty_cycle/hbm_* numbers. Tolerant of whitespace/ordering;
// anything unparsable yields no chips (exit 1 below).
bool parse_fake(const std::string& text, std::vector<Chip>* chips) {
  size_t pos = 0;
  while ((pos = text.find("\"device_id\"", pos)) != std::string::npos) {
    Chip c;
    auto grab = [&](const char* key, double* out_d, long long* out_ll) {
      size_t start = text.rfind('{', pos);
      size_t end = text.find('}', pos);
      if (start == std::string::npos || end == std::string::npos) return;
      size_t k = text.find(key, start);
      if (k == std::string::npos || k > end) return;
      size_t colon = text.find(':', k);
      if (colon == std::string::npos || colon > end) return;
      const char* s = text.c_str() + colon + 1;
      if (out_d) *out_d = strtod(s, nullptr);
      if (out_ll) *out_ll = strtoll(s, nullptr, 10);
    };
    double id = 0;
    grab("\"device_id\"", &id, nullptr);
    c.device_id = static_cast<int>(id);
    grab("\"duty_cycle\"", &c.duty_cycle, nullptr);
    grab("\"hbm_used\"", nullptr, &c.hbm_used);
    grab("\"hbm_total\"", nullptr, &c.hbm_total);
    chips->push_back(c);
    pos = text.find('}', pos);
    if (pos == std::string::npos) break;
  }
  return !chips->empty();
}

bool probe_fake(const char* path, std::vector<Chip>* chips) {
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  std::string text;
  char buf[4096];
  size_t n;
  while ((n = fread(buf, 1, sizeof buf, f)) > 0) text.append(buf, n);
  fclose(f);
  return parse_fake(text, chips);
}

bool probe_devnodes(const char* pattern, std::vector<Chip>* chips) {
  glob_t g;
  if (glob(pattern, 0, nullptr, &g) != 0) return false;
  for (size_t i = 0; i < g.gl_pathc; ++i) {
    Chip c;
    // device id = trailing integer of the node name (accel3 -> 3)
    const char* name = g.gl_pathv[i];
    const char* p = name + strlen(name);
    while (p > name && isdigit(static_cast<unsigned char>(p[-1]))) --p;
    c.device_id = atoi(p);
    chips->push_back(c);
  }
  globfree(&g);
  return !chips->empty();
}

}  // namespace

int main(int argc, char** argv) {
  const char* fake = getenv("TPUPROBE_FAKE");
  const char* dev_glob = getenv("TPUPROBE_DEV_GLOB");
  int interval_s = 0;  // 0 = --once
  for (int i = 1; i < argc; ++i) {
    if (!strcmp(argv[i], "--fake") && i + 1 < argc) fake = argv[++i];
    else if (!strcmp(argv[i], "--interval") && i + 1 < argc)
      interval_s = atoi(argv[++i]);
    else if (!strcmp(argv[i], "--once")) interval_s = 0;
    else if (!strcmp(argv[i], "--help")) {
      puts("tpuprobe [--once] [--interval SECONDS] [--fake FILE]");
      return 0;
    }
  }
  if (!dev_glob) dev_glob = "/dev/accel*";

  do {
    std::vector<Chip> chips;
    bool ok = fake ? probe_fake(fake, &chips) : probe_devnodes(dev_glob, &chips);
    if (!ok && !fake) ok = probe_fake("/tmp/tpuprobe_fake.json", &chips);
    if (!ok) {
      fputs("{\"chips\":[]}\n", stdout);
      fflush(stdout);
      if (interval_s == 0) return 1;
    } else {
      emit(chips);
    }
    if (interval_s > 0) sleep(interval_s);
  } while (interval_s > 0);
  return 0;
}
